"""OpenInference span semantic conventions.

Attribute-name and value parity with the reference's
``internal/tracing/openinference`` package:

- constants: ``openinference.go:18-240`` (span kind, llm.*, input/output,
  token counts incl. prompt/completion details, embeddings, tools)
- request builders: ``openai/request_attrs.go:32-340`` (chat, embeddings,
  completions)
- response builders: ``openai/response_attrs.go:20-170``
- privacy config: ``config.go`` (OPENINFERENCE_HIDE_* env vars,
  ``__REDACTED__`` sentinel, base64 image cap)
- error typing: ``response_error.go`` (HTTP status → OpenAI SDK-style
  exception class names)

Everything operates on plain request/response dicts (this gateway's
schema layer is dict-based) and returns ``{attr_name: value}`` maps to
merge into a ``Span``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any

# -- semconv constants (openinference.go) --------------------------------
SPAN_KIND = "openinference.span.kind"
SPAN_KIND_LLM = "LLM"
SPAN_KIND_EMBEDDING = "EMBEDDING"
LLM_SYSTEM = "llm.system"
LLM_SYSTEM_OPENAI = "openai"
LLM_SYSTEM_ANTHROPIC = "anthropic"
LLM_MODEL_NAME = "llm.model_name"
LLM_INVOCATION_PARAMETERS = "llm.invocation_parameters"
INPUT_VALUE = "input.value"
INPUT_MIME_TYPE = "input.mime_type"
OUTPUT_VALUE = "output.value"
OUTPUT_MIME_TYPE = "output.mime_type"
MIME_TYPE_JSON = "application/json"
LLM_INPUT_MESSAGES = "llm.input_messages"
LLM_OUTPUT_MESSAGES = "llm.output_messages"
MESSAGE_ROLE = "message.role"
MESSAGE_CONTENT = "message.content"
MESSAGE_TOOL_CALLS = "message.tool_calls"
TOOL_CALL_ID = "tool_call.id"
TOOL_CALL_FUNCTION_NAME = "tool_call.function.name"
TOOL_CALL_FUNCTION_ARGUMENTS = "tool_call.function.arguments"
LLM_TOOLS = "llm.tools"
LLM_PROMPTS = "llm.prompts"
LLM_CHOICES = "llm.choices"
LLM_TOKEN_COUNT_PROMPT = "llm.token_count.prompt"
LLM_TOKEN_COUNT_COMPLETION = "llm.token_count.completion"
LLM_TOKEN_COUNT_TOTAL = "llm.token_count.total"
LLM_TOKEN_COUNT_PROMPT_CACHE_HIT = (
    "llm.token_count.prompt_details.cache_read")
LLM_TOKEN_COUNT_PROMPT_CACHE_WRITE = (
    "llm.token_count.prompt_details.cache_creation")
LLM_TOKEN_COUNT_PROMPT_AUDIO = "llm.token_count.prompt_details.audio"
LLM_TOKEN_COUNT_COMPLETION_REASONING = (
    "llm.token_count.completion_details.reasoning")
LLM_TOKEN_COUNT_COMPLETION_AUDIO = (
    "llm.token_count.completion_details.audio")
EMBEDDING_MODEL_NAME = "embedding.model_name"
EMBEDDING_INVOCATION_PARAMETERS = "embedding.invocation_parameters"
EMBEDDING_EMBEDDINGS = "embedding.embeddings"

REDACTED = "__REDACTED__"


def input_message_attr(i: int, suffix: str) -> str:
    return f"{LLM_INPUT_MESSAGES}.{i}.{suffix}"


def input_message_content_attr(i: int, j: int, suffix: str) -> str:
    return f"{LLM_INPUT_MESSAGES}.{i}.message.contents.{j}." \
           f"message_content.{suffix}"


def input_message_tool_call_attr(i: int, j: int, suffix: str) -> str:
    return f"{LLM_INPUT_MESSAGES}.{i}.{MESSAGE_TOOL_CALLS}.{j}.{suffix}"


def output_message_attr(i: int, suffix: str) -> str:
    return f"{LLM_OUTPUT_MESSAGES}.{i}.{suffix}"


def output_message_content_attr(i: int, j: int, suffix: str) -> str:
    return f"{LLM_OUTPUT_MESSAGES}.{i}.message.contents.{j}." \
           f"message_content.{suffix}"


def output_message_tool_call_attr(i: int, j: int, suffix: str) -> str:
    return f"{LLM_OUTPUT_MESSAGES}.{i}.{MESSAGE_TOOL_CALLS}.{j}.{suffix}"


def input_tools_attr(i: int) -> str:
    return f"{LLM_TOOLS}.{i}.tool.json_schema"


def embedding_text_attr(i: int) -> str:
    return f"{EMBEDDING_EMBEDDINGS}.{i}.embedding.text"


def embedding_vector_attr(i: int) -> str:
    return f"{EMBEDDING_EMBEDDINGS}.{i}.embedding.vector"


def prompt_text_attr(i: int) -> str:
    return f"{LLM_PROMPTS}.{i}.prompt.text"


def choice_text_attr(i: int) -> str:
    return f"{LLM_CHOICES}.{i}.completion.text"


# -- privacy config (config.go) ------------------------------------------
def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name, "")
    if not v:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


@dataclass(frozen=True)
class TraceConfig:
    hide_llm_invocation_parameters: bool = False
    hide_inputs: bool = False
    hide_outputs: bool = False
    hide_input_messages: bool = False
    hide_output_messages: bool = False
    hide_input_images: bool = False
    hide_input_text: bool = False
    hide_output_text: bool = False
    hide_embeddings_text: bool = False
    hide_embeddings_vectors: bool = False
    hide_prompts: bool = False
    hide_choices: bool = False
    base64_image_max_length: int = 32000

    @staticmethod
    def from_env() -> "TraceConfig":
        try:
            maxlen = int(os.environ.get(
                "OPENINFERENCE_BASE64_IMAGE_MAX_LENGTH", "32000"))
        except ValueError:
            maxlen = 32000
        return TraceConfig(
            hide_llm_invocation_parameters=_env_bool(
                "OPENINFERENCE_HIDE_LLM_INVOCATION_PARAMETERS"),
            hide_inputs=_env_bool("OPENINFERENCE_HIDE_INPUTS"),
            hide_outputs=_env_bool("OPENINFERENCE_HIDE_OUTPUTS"),
            hide_input_messages=_env_bool(
                "OPENINFERENCE_HIDE_INPUT_MESSAGES"),
            hide_output_messages=_env_bool(
                "OPENINFERENCE_HIDE_OUTPUT_MESSAGES"),
            hide_input_images=_env_bool("OPENINFERENCE_HIDE_INPUT_IMAGES"),
            hide_input_text=_env_bool("OPENINFERENCE_HIDE_INPUT_TEXT"),
            hide_output_text=_env_bool("OPENINFERENCE_HIDE_OUTPUT_TEXT"),
            hide_embeddings_text=_env_bool(
                "OPENINFERENCE_HIDE_EMBEDDINGS_TEXT"),
            hide_embeddings_vectors=_env_bool(
                "OPENINFERENCE_HIDE_EMBEDDINGS_VECTORS"),
            hide_prompts=_env_bool("OPENINFERENCE_HIDE_PROMPTS"),
            hide_choices=_env_bool("OPENINFERENCE_HIDE_CHOICES"),
            base64_image_max_length=maxlen,
        )


# -- error typing (response_error.go) ------------------------------------
def error_type_for_status(status: int) -> str:
    """HTTP status → OpenAI SDK exception class name."""
    if status == 400:
        return "BadRequestError"
    if status == 401:
        return "AuthenticationError"
    if status == 403:
        return "PermissionDeniedError"
    if status == 404:
        return "NotFoundError"
    if status == 429:
        return "RateLimitError"
    if status >= 500:
        return "InternalServerError"
    return "Error"


# -- request builders -----------------------------------------------------
def _content_text(content: Any) -> str | None:
    """Plain-string content, or None when it's a parts list."""
    if isinstance(content, str):
        return content
    return None


def _maybe_truncate_image(url: str, cfg: TraceConfig) -> str | None:
    """None = drop the image attribute entirely (reference drops base64
    URLs longer than the cap rather than truncating them)."""
    if url.startswith("data:") and len(url) > cfg.base64_image_max_length:
        return None
    return url


def chat_request_attributes(
    req: dict[str, Any],
    raw: str | bytes,
    cfg: TraceConfig,
    system: str = LLM_SYSTEM_OPENAI,
) -> dict[str, Any]:
    """OpenAI-shape chat request → attrs (request_attrs.go:32-207).
    ``system`` distinguishes the Anthropic /v1/messages front."""
    attrs: dict[str, Any] = {
        SPAN_KIND: SPAN_KIND_LLM,
        LLM_SYSTEM: system,
        LLM_MODEL_NAME: str(req.get("model", "")),
    }
    if cfg.hide_inputs:
        attrs[INPUT_VALUE] = REDACTED
    else:
        attrs[INPUT_VALUE] = (
            raw.decode("utf-8", "replace")
            if isinstance(raw, bytes) else raw
        )
        attrs[INPUT_MIME_TYPE] = MIME_TYPE_JSON
    if not cfg.hide_llm_invocation_parameters:
        params = {k: v for k, v in req.items()
                  if k not in ("messages", "tools")}
        attrs[LLM_INVOCATION_PARAMETERS] = json.dumps(params)
    if not cfg.hide_inputs and not cfg.hide_input_messages:
        for i, msg in enumerate(req.get("messages") or ()):
            if not isinstance(msg, dict):
                continue
            role = str(msg.get("role", ""))
            attrs[input_message_attr(i, MESSAGE_ROLE)] = role
            content = msg.get("content")
            text = _content_text(content)
            if text is not None:
                attrs[input_message_attr(i, MESSAGE_CONTENT)] = (
                    REDACTED if cfg.hide_input_text else text
                )
            elif isinstance(content, list):
                for j, part in enumerate(content):
                    if not isinstance(part, dict):
                        continue
                    ptype = part.get("type", "")
                    if ptype == "text":
                        attrs[input_message_content_attr(
                            i, j, "text")] = (
                            REDACTED if cfg.hide_input_text
                            else str(part.get("text", ""))
                        )
                        attrs[input_message_content_attr(
                            i, j, "type")] = "text"
                    elif (ptype == "image_url"
                          and not cfg.hide_input_images):
                        url = str(
                            (part.get("image_url") or {}).get("url", ""))
                        kept = _maybe_truncate_image(url, cfg)
                        if kept is not None:
                            key = input_message_content_attr(
                                i, j, "image.image.url")
                            attrs[key] = kept
                            attrs[input_message_content_attr(
                                i, j, "type")] = "image"
            for j, tc in enumerate(msg.get("tool_calls") or ()):
                if not isinstance(tc, dict):
                    continue
                if tc.get("id"):
                    attrs[input_message_tool_call_attr(
                        i, j, TOOL_CALL_ID)] = str(tc["id"])
                fn = tc.get("function") or {}
                attrs[input_message_tool_call_attr(
                    i, j, TOOL_CALL_FUNCTION_NAME)] = str(
                    fn.get("name", ""))
                attrs[input_message_tool_call_attr(
                    i, j, TOOL_CALL_FUNCTION_ARGUMENTS)] = str(
                    fn.get("arguments", ""))
    for i, tool in enumerate(req.get("tools") or ()):
        attrs[input_tools_attr(i)] = json.dumps(tool)
    return attrs


def _usage_attributes(usage: dict[str, Any]) -> dict[str, Any]:
    """Token counts incl. prompt/completion details
    (response_attrs.go:56-78); accepts OpenAI and Anthropic field
    names."""
    attrs: dict[str, Any] = {}
    pt = usage.get("prompt_tokens") or usage.get("input_tokens") or 0
    ct = usage.get("completion_tokens") or usage.get("output_tokens") or 0
    tt = usage.get("total_tokens") or 0
    if not tt and (pt or ct):
        tt = pt + ct
    if pt:
        attrs[LLM_TOKEN_COUNT_PROMPT] = int(pt)
    ptd = usage.get("prompt_tokens_details") or {}
    if ptd.get("audio_tokens"):
        attrs[LLM_TOKEN_COUNT_PROMPT_AUDIO] = int(ptd["audio_tokens"])
    cache_read = (ptd.get("cached_tokens")
                  or usage.get("cache_read_input_tokens") or 0)
    if cache_read:
        attrs[LLM_TOKEN_COUNT_PROMPT_CACHE_HIT] = int(cache_read)
    cache_write = (ptd.get("cache_creation_tokens")
                   or usage.get("cache_creation_input_tokens") or 0)
    if cache_write:
        attrs[LLM_TOKEN_COUNT_PROMPT_CACHE_WRITE] = int(cache_write)
    if ct:
        attrs[LLM_TOKEN_COUNT_COMPLETION] = int(ct)
    ctd = usage.get("completion_tokens_details") or {}
    if ctd.get("audio_tokens"):
        attrs[LLM_TOKEN_COUNT_COMPLETION_AUDIO] = int(ctd["audio_tokens"])
    if ctd.get("reasoning_tokens"):
        attrs[LLM_TOKEN_COUNT_COMPLETION_REASONING] = int(
            ctd["reasoning_tokens"])
    if tt:
        attrs[LLM_TOKEN_COUNT_TOTAL] = int(tt)
    return attrs


def chat_response_attributes(
    resp: dict[str, Any], cfg: TraceConfig
) -> dict[str, Any]:
    """OpenAI-shape chat response → attrs (response_attrs.go:20-79)."""
    attrs: dict[str, Any] = {}
    if resp.get("model"):
        attrs[LLM_MODEL_NAME] = str(resp["model"])
    if cfg.hide_outputs:
        attrs[OUTPUT_VALUE] = REDACTED
    else:
        attrs[OUTPUT_VALUE] = json.dumps(resp)
        attrs[OUTPUT_MIME_TYPE] = MIME_TYPE_JSON
    if not cfg.hide_outputs and not cfg.hide_output_messages:
        for i, choice in enumerate(resp.get("choices") or ()):
            msg = choice.get("message") or {}
            if msg.get("role"):
                attrs[output_message_attr(i, MESSAGE_ROLE)] = str(
                    msg["role"])
            content = msg.get("content")
            if isinstance(content, str) and content:
                attrs[output_message_attr(i, MESSAGE_CONTENT)] = (
                    REDACTED if cfg.hide_output_text else content
                )
            for j, tc in enumerate(msg.get("tool_calls") or ()):
                if tc.get("id"):
                    attrs[output_message_tool_call_attr(
                        i, j, TOOL_CALL_ID)] = str(tc["id"])
                fn = tc.get("function") or {}
                attrs[output_message_tool_call_attr(
                    i, j, TOOL_CALL_FUNCTION_NAME)] = str(
                    fn.get("name", ""))
                attrs[output_message_tool_call_attr(
                    i, j, TOOL_CALL_FUNCTION_ARGUMENTS)] = str(
                    fn.get("arguments", ""))
    attrs.update(_usage_attributes(resp.get("usage") or {}))
    return attrs


def anthropic_response_attributes(
    resp: dict[str, Any], cfg: TraceConfig
) -> dict[str, Any]:
    """Anthropic /v1/messages response → the same output attrs (so the
    Anthropic front traces identically to chat)."""
    attrs: dict[str, Any] = {}
    if resp.get("model"):
        attrs[LLM_MODEL_NAME] = str(resp["model"])
    if cfg.hide_outputs:
        attrs[OUTPUT_VALUE] = REDACTED
    else:
        attrs[OUTPUT_VALUE] = json.dumps(resp)
        attrs[OUTPUT_MIME_TYPE] = MIME_TYPE_JSON
    if not cfg.hide_outputs and not cfg.hide_output_messages:
        attrs[output_message_attr(0, MESSAGE_ROLE)] = str(
            resp.get("role", "assistant"))
        texts = [b.get("text", "") for b in resp.get("content") or ()
                 if isinstance(b, dict) and b.get("type") == "text"]
        if any(texts):
            attrs[output_message_attr(0, MESSAGE_CONTENT)] = (
                REDACTED if cfg.hide_output_text else "".join(texts)
            )
        tool_uses = [b for b in resp.get("content") or ()
                     if isinstance(b, dict)
                     and b.get("type") == "tool_use"]
        for j, tu in enumerate(tool_uses):
            if tu.get("id"):
                attrs[output_message_tool_call_attr(
                    0, j, TOOL_CALL_ID)] = str(tu["id"])
            attrs[output_message_tool_call_attr(
                0, j, TOOL_CALL_FUNCTION_NAME)] = str(tu.get("name", ""))
            attrs[output_message_tool_call_attr(
                0, j, TOOL_CALL_FUNCTION_ARGUMENTS)] = json.dumps(
                tu.get("input") or {})
    attrs.update(_usage_attributes(resp.get("usage") or {}))
    return attrs


def embeddings_request_attributes(
    req: dict[str, Any], raw: str | bytes, cfg: TraceConfig
) -> dict[str, Any]:
    """Embeddings request → attrs (request_attrs.go:223-300)."""
    attrs: dict[str, Any] = {
        SPAN_KIND: SPAN_KIND_EMBEDDING,
        EMBEDDING_MODEL_NAME: str(req.get("model", "")),
    }
    if cfg.hide_inputs:
        attrs[INPUT_VALUE] = REDACTED
    else:
        attrs[INPUT_VALUE] = (
            raw.decode("utf-8", "replace")
            if isinstance(raw, bytes) else raw
        )
        attrs[INPUT_MIME_TYPE] = MIME_TYPE_JSON
    if not cfg.hide_llm_invocation_parameters:
        params = {k: v for k, v in req.items() if k != "input"}
        attrs[EMBEDDING_INVOCATION_PARAMETERS] = json.dumps(params)
    if not cfg.hide_inputs and not cfg.hide_embeddings_text:
        inputs = req.get("input")
        if isinstance(inputs, str):
            attrs[embedding_text_attr(0)] = inputs
        elif isinstance(inputs, list):
            for i, text in enumerate(inputs):
                if isinstance(text, str):
                    attrs[embedding_text_attr(i)] = text
    return attrs


def embeddings_response_attributes(
    resp: dict[str, Any], cfg: TraceConfig
) -> dict[str, Any]:
    """Embeddings response → attrs (response_attrs.go:82-119)."""
    attrs: dict[str, Any] = {}
    if resp.get("model"):
        attrs[EMBEDDING_MODEL_NAME] = str(resp["model"])
    if cfg.hide_outputs:
        attrs[OUTPUT_VALUE] = REDACTED
    else:
        attrs[OUTPUT_MIME_TYPE] = MIME_TYPE_JSON
    if not cfg.hide_outputs and not cfg.hide_embeddings_vectors:
        for i, item in enumerate(resp.get("data") or ()):
            emb = item.get("embedding")
            if isinstance(emb, list) and emb:
                attrs[embedding_vector_attr(i)] = [
                    float(x) for x in emb]
    usage = resp.get("usage") or {}
    if usage.get("prompt_tokens"):
        attrs[LLM_TOKEN_COUNT_PROMPT] = int(usage["prompt_tokens"])
    if usage.get("total_tokens"):
        attrs[LLM_TOKEN_COUNT_TOTAL] = int(usage["total_tokens"])
    return attrs


def completion_request_attributes(
    req: dict[str, Any], raw: str | bytes, cfg: TraceConfig
) -> dict[str, Any]:
    """Legacy /v1/completions request → attrs
    (request_attrs.go:309-350)."""
    attrs: dict[str, Any] = {
        SPAN_KIND: SPAN_KIND_LLM,
        LLM_SYSTEM: LLM_SYSTEM_OPENAI,
        LLM_MODEL_NAME: str(req.get("model", "")),
    }
    if cfg.hide_inputs:
        attrs[INPUT_VALUE] = REDACTED
    else:
        attrs[INPUT_VALUE] = (
            raw.decode("utf-8", "replace")
            if isinstance(raw, bytes) else raw
        )
        attrs[INPUT_MIME_TYPE] = MIME_TYPE_JSON
    if not cfg.hide_llm_invocation_parameters:
        params = {k: v for k, v in req.items() if k != "prompt"}
        attrs[LLM_INVOCATION_PARAMETERS] = json.dumps(params)
    if not cfg.hide_inputs and not cfg.hide_prompts:
        prompt = req.get("prompt")
        if isinstance(prompt, str):
            attrs[prompt_text_attr(0)] = prompt
        elif isinstance(prompt, list):
            for i, p in enumerate(prompt):
                if isinstance(p, str):
                    attrs[prompt_text_attr(i)] = p
    return attrs


def completion_response_attributes(
    resp: dict[str, Any], cfg: TraceConfig
) -> dict[str, Any]:
    """Legacy /v1/completions response → attrs
    (response_attrs.go:141-172)."""
    attrs: dict[str, Any] = {}
    if resp.get("model"):
        attrs[LLM_MODEL_NAME] = str(resp["model"])
    if cfg.hide_outputs:
        attrs[OUTPUT_VALUE] = REDACTED
    else:
        attrs[OUTPUT_VALUE] = json.dumps(resp)
        attrs[OUTPUT_MIME_TYPE] = MIME_TYPE_JSON
    if not cfg.hide_outputs and not cfg.hide_choices:
        for i, choice in enumerate(resp.get("choices") or ()):
            text = choice.get("text")
            if isinstance(text, str) and text:
                attrs[choice_text_attr(i)] = text
    attrs.update(_usage_attributes(resp.get("usage") or {}))
    return attrs


SPAN_KIND_RERANKER = "RERANKER"
LLM_SYSTEM_COHERE = "cohere"
RERANKER_MODEL_NAME = "reranker.model_name"
RERANKER_QUERY = "reranker.query"
RERANKER_TOP_K = "reranker.top_k"


def reranker_input_doc_attr(i: int) -> str:
    """Flattened input-document key (openinference/rerank.go:45-49)."""
    return f"reranker.input_documents.{i}.document.content"


def reranker_output_doc_attr(i: int) -> str:
    return f"reranker.output_documents.{i}.document.score"


def rerank_request_attributes(
    req: dict[str, Any], raw: str | bytes, cfg: TraceConfig
) -> dict[str, Any]:
    """Cohere /v2/rerank request → OpenInference RERANKER attrs
    (reference openinference/cohere/rerank.go:84-123)."""
    attrs: dict[str, Any] = {
        LLM_SYSTEM: LLM_SYSTEM_COHERE,
        SPAN_KIND: SPAN_KIND_RERANKER,
    }
    if req.get("model"):
        attrs[RERANKER_MODEL_NAME] = str(req["model"])
    if req.get("top_n") is not None:
        attrs[RERANKER_TOP_K] = int(req["top_n"])
    if req.get("query"):
        attrs[RERANKER_QUERY] = str(req["query"])
    if cfg.hide_inputs:
        attrs[INPUT_VALUE] = REDACTED
    else:
        attrs[INPUT_VALUE] = (
            raw.decode("utf-8", "replace")
            if isinstance(raw, bytes) else raw
        )
        attrs[INPUT_MIME_TYPE] = MIME_TYPE_JSON
        for i, doc in enumerate(req.get("documents") or ()):
            text = doc if isinstance(doc, str) else (
                doc.get("text", "") if isinstance(doc, dict) else "")
            if text:
                attrs[reranker_input_doc_attr(i)] = text
    return attrs


def rerank_response_attributes(
    resp: dict[str, Any], cfg: TraceConfig
) -> dict[str, Any]:
    """Cohere /v2/rerank response → attrs (rerank.go:125-154): per-result
    relevance scores as output documents; token counts survive
    hide_outputs."""
    attrs: dict[str, Any] = {}
    if cfg.hide_outputs:
        attrs[OUTPUT_VALUE] = REDACTED
    else:
        attrs[OUTPUT_VALUE] = json.dumps(resp)
        attrs[OUTPUT_MIME_TYPE] = MIME_TYPE_JSON
        for i, res in enumerate(resp.get("results") or ()):
            if isinstance(res, dict) and "relevance_score" in res:
                attrs[reranker_output_doc_attr(i)] = float(
                    res["relevance_score"])
    tokens = ((resp.get("meta") or {}).get("tokens") or {})
    inp = tokens.get("input_tokens")
    out = tokens.get("output_tokens")
    if inp:
        attrs[LLM_TOKEN_COUNT_PROMPT] = int(inp)
    if out:
        attrs[LLM_TOKEN_COUNT_COMPLETION] = int(out)
    if inp or out:
        attrs[LLM_TOKEN_COUNT_TOTAL] = int(inp or 0) + int(out or 0)
    return attrs


class StreamAccumulator:
    """Reconstructs a response dict from front-schema SSE bytes so
    streamed requests get the same output attributes as unary ones
    (reference openai/sse_converter.go). Feed the bytes already written
    to the client; ``response()`` returns an OpenAI- or Anthropic-shaped
    dict depending on the front schema observed."""

    def __init__(self) -> None:
        from aigw_tpu.translate.sse import SSEParser

        self._parser = SSEParser()
        self._model = ""
        self._role = ""
        self._texts: dict[int, list[str]] = {}
        self._tool_calls: dict[int, dict[int, dict[str, Any]]] = {}
        self._finish: dict[int, str] = {}
        self._usage: dict[str, Any] = {}
        self._anthropic = False
        self._completion = False  # legacy text-completion chunks seen
        self._anth_blocks: dict[int, dict[str, Any]] = {}

    def feed(self, data: bytes) -> None:
        """Never raises: upstream-controlled bytes feed this from the
        client-streaming hot loop, and telemetry must not sever the
        stream."""
        try:
            events = self._parser.feed(data)
        except Exception:  # noqa: BLE001
            return
        for ev in events:
            if not ev.data or ev.data.strip() == "[DONE]":
                continue
            try:
                msg = json.loads(ev.data)
                if not isinstance(msg, dict):
                    continue
                if "type" in msg and "choices" not in msg:
                    self._feed_anthropic(msg)
                else:
                    self._feed_openai(msg)
            except Exception:  # noqa: BLE001 — malformed upstream event
                continue

    def _feed_openai(self, msg: dict[str, Any]) -> None:
        self._model = msg.get("model") or self._model
        if isinstance(msg.get("usage"), dict):
            self._usage.update(msg["usage"])
        for choice in msg.get("choices") or ():
            if not isinstance(choice, dict):
                continue
            idx = int(choice.get("index") or 0)
            # legacy /v1/completions chunks carry text directly
            if isinstance(choice.get("text"), str) and "delta" not in choice:
                self._completion = True
                self._texts.setdefault(idx, []).append(choice["text"])
                if choice.get("finish_reason"):
                    self._finish[idx] = choice["finish_reason"]
                continue
            delta = choice.get("delta") or {}
            if delta.get("role"):
                self._role = delta["role"]
            if isinstance(delta.get("content"), str):
                self._texts.setdefault(idx, []).append(delta["content"])
            for tc in delta.get("tool_calls") or ():
                ti = int(tc.get("index", 0))
                acc = self._tool_calls.setdefault(idx, {}).setdefault(
                    ti, {"id": "", "function": {"name": "",
                                                "arguments": ""}})
                if tc.get("id"):
                    acc["id"] = tc["id"]
                fn = tc.get("function") or {}
                if fn.get("name"):
                    acc["function"]["name"] = fn["name"]
                if fn.get("arguments"):
                    acc["function"]["arguments"] += fn["arguments"]
            if choice.get("finish_reason"):
                self._finish[idx] = choice["finish_reason"]

    def _feed_anthropic(self, msg: dict[str, Any]) -> None:
        self._anthropic = True
        t = msg.get("type")
        if t == "message_start":
            m = msg.get("message") or {}
            self._model = m.get("model") or self._model
            self._role = m.get("role", "assistant")
            if isinstance(m.get("usage"), dict):
                self._usage.update(m["usage"])
        elif t == "content_block_start":
            idx = int(msg.get("index", 0))
            self._anth_blocks[idx] = dict(
                msg.get("content_block") or {})
            self._anth_blocks[idx].setdefault("_json", [])
        elif t == "content_block_delta":
            idx = int(msg.get("index", 0))
            block = self._anth_blocks.setdefault(
                idx, {"type": "text", "_json": []})
            d = msg.get("delta") or {}
            if d.get("type") == "text_delta":
                block["text"] = block.get("text", "") + d.get("text", "")
            elif d.get("type") == "input_json_delta":
                block.setdefault("_json", []).append(
                    d.get("partial_json", ""))
        elif t == "message_delta":
            if isinstance(msg.get("usage"), dict):
                self._usage.update(msg["usage"])

    def response(self) -> dict[str, Any] | None:
        if self._anthropic:
            content: list[dict[str, Any]] = []
            for idx in sorted(self._anth_blocks):
                block = dict(self._anth_blocks[idx])
                parts = block.pop("_json", [])
                if block.get("type") == "tool_use" and parts:
                    try:
                        block["input"] = json.loads("".join(parts))
                    except ValueError:
                        pass
                content.append(block)
            if not (content or self._model or self._usage):
                return None
            return {
                "model": self._model,
                "role": self._role or "assistant",
                "content": content,
                "usage": self._usage,
            }
        if not (self._texts or self._tool_calls or self._model
                or self._usage):
            return None
        if self._completion:
            return {
                "model": self._model,
                "choices": [
                    {"index": idx, "text": "".join(self._texts[idx]),
                     "finish_reason": self._finish.get(idx)}
                    for idx in sorted(self._texts)
                ],
                "usage": self._usage,
            }
        choices = []
        for idx in sorted(set(self._texts) | set(self._tool_calls)
                          | set(self._finish) | {0}):
            msg: dict[str, Any] = {"role": self._role or "assistant"}
            if idx in self._texts:
                msg["content"] = "".join(self._texts[idx])
            if idx in self._tool_calls:
                msg["tool_calls"] = [
                    self._tool_calls[idx][ti]
                    for ti in sorted(self._tool_calls[idx])
                ]
            choices.append({
                "index": idx,
                "message": msg,
                "finish_reason": self._finish.get(idx),
            })
        return {
            "model": self._model,
            "choices": choices,
            "usage": self._usage,
        }
