"""XLA compile tracking — one shared hook instead of per-test hacks.

Two complementary sources, because neither alone answers both questions
operators and tests ask:

1. **Process-wide compile events** via ``jax.monitoring``: JAX records a
   ``/jax/core/compile/backend_compile_duration`` event for every XLA
   backend compile (lowering and jaxpr-trace durations ride sibling
   keys). One module-level listener counts them and sums their wall
   time — the "did anything compile, and how long did it cost" counter
   exported on ``/metrics`` and ``/state``.

2. **Per-engine program accounting** via the jit caches of the engine's
   REGISTERED hot-path callables (prefill ladder, decode/verify scans,
   row-update scatters, CoW page copy). ``_cache_size()`` per function is
   the shape-key-level view: which program family grew, and by how many
   compiled shapes. This is what the compile tripwire tests assert on —
   it is immune to other engines compiling concurrently in the same
   process (the monitoring counter is not).

jax.monitoring listeners are process-global and cannot be individually
removed, so installation happens once per process and trackers read
deltas against a baseline taken at construction/checkpoint time.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

#: jax.monitoring duration keys counted as "an XLA compile happened"
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_installed = False
_compile_count = 0
_compile_ms = 0.0
_last_compile_at = 0.0


def _on_duration(event: str, duration_secs: float, **_kw: Any) -> None:
    global _compile_count, _compile_ms, _last_compile_at
    if event != _COMPILE_EVENT:
        return
    with _lock:
        _compile_count += 1
        _compile_ms += duration_secs * 1e3
        _last_compile_at = time.time()


def install() -> bool:
    """Register the process-wide compile listener (idempotent). Returns
    False when jax.monitoring is unavailable — the per-engine program
    accounting still works without it."""
    global _installed
    with _lock:
        if _installed:
            return True
    try:
        import jax.monitoring as monitoring

        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:  # noqa: BLE001 — telemetry must never break serving
        return False
    with _lock:
        _installed = True
    return True


def compile_count() -> int:
    """XLA backend compiles observed process-wide since install()."""
    with _lock:
        return _compile_count


def compile_ms() -> float:
    with _lock:
        return _compile_ms


class CompileTracker:
    """Per-engine compile accounting over registered jitted callables,
    plus a delta view of the process-wide monitoring counter."""

    def __init__(self) -> None:
        self.monitoring = install()
        self._fns: dict[str, Callable] = {}
        self._base_count = compile_count()
        self._base_ms = compile_ms()

    # -- registration -----------------------------------------------------
    def register(self, name: str, fn: Callable) -> Callable:
        """Track ``fn`` (a jax.jit product) under ``name``; returns it so
        registration composes at the creation site."""
        self._fns[name] = fn
        return fn

    # -- per-engine program view (the tripwire surface) -------------------
    @staticmethod
    def _size(fn: Callable) -> int:
        get = getattr(fn, "_cache_size", None)
        if get is None:
            return 0
        try:
            return int(get())
        except Exception:  # noqa: BLE001 — private API; fail soft
            return 0

    def programs(self) -> dict[str, int]:
        """Registered program family → compiled-shape count."""
        return {name: self._size(fn) for name, fn in self._fns.items()}

    def program_count(self) -> int:
        return sum(self.programs().values())

    # -- process-wide event view ------------------------------------------
    def compiles(self) -> int:
        """Compile events observed since this tracker was constructed."""
        return compile_count() - self._base_count

    def compiles_total_ms(self) -> float:
        return compile_ms() - self._base_ms

    # -- checkpoint/delta (warmup tripwires) ------------------------------
    def checkpoint(self) -> tuple[int, int]:
        return (self.program_count(), compile_count())

    def compiles_since(self, cp: tuple[int, int]) -> int:
        """New compiled programs across this engine's registered
        callables since ``cp`` — the precise zero-compile-after-warmup
        assertion (other engines in the process don't pollute it)."""
        return self.program_count() - cp[0]

    def snapshot(self) -> dict[str, Any]:
        return {
            "monitoring": self.monitoring,
            "xla_compiles": self.compiles(),
            "xla_compile_ms": round(self.compiles_total_ms(), 3),
            "programs": self.programs(),
            "program_count": self.program_count(),
        }
