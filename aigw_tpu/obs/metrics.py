"""GenAI metrics with OTel semantic-convention names.

Reference: internal/metrics/genai.go:14-24 records
``gen_ai.client.token.usage``, ``gen_ai.server.request.duration``,
``gen_ai.server.time_to_first_token``, ``gen_ai.server.time_per_output_token``
with operation/provider/model/token-type attributes, exported via Prometheus
(+ optional OTLP). We register the same instruments on a prometheus_client
registry (dots become underscores per the Prometheus naming translation the
OTel exporter applies).
"""

from __future__ import annotations

import bisect
import time
from dataclasses import dataclass, field

from prometheus_client import CollectorRegistry, Counter, Histogram, generate_latest

from aigw_tpu.gateway.costs import TokenUsage

_LATENCY_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0,
)
_TOKEN_BUCKETS = (
    1, 4, 16, 64, 256, 1024, 4096, 16384, 65536, 262144, 1048576,
)


class GenAIMetrics:
    """Instrument set shared by the gateway and tpuserve."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        labels = ["gen_ai_operation_name", "gen_ai_provider_name",
                  "gen_ai_request_model", "gen_ai_response_model"]
        self.token_usage = Histogram(
            "gen_ai_client_token_usage",
            "Number of input/output tokens used per request",
            labels + ["gen_ai_token_type"],
            registry=self.registry,
            buckets=_TOKEN_BUCKETS,
        )
        self.request_duration = Histogram(
            "gen_ai_server_request_duration_seconds",
            "End-to-end request duration",
            labels + ["error_type"],
            registry=self.registry,
            buckets=_LATENCY_BUCKETS,
        )
        self.time_to_first_token = Histogram(
            "gen_ai_server_time_to_first_token_seconds",
            "Time until the first streamed token",
            labels,
            registry=self.registry,
            buckets=_LATENCY_BUCKETS,
        )
        self.time_per_output_token = Histogram(
            "gen_ai_server_time_per_output_token_seconds",
            "Inter-token latency for streamed tokens",
            labels,
            registry=self.registry,
            buckets=_LATENCY_BUCKETS,
        )
        self.requests_total = Counter(
            "aigw_requests_total",
            "Requests by route/backend/status",
            ["route", "backend", "status"],
            registry=self.registry,
        )
        self.retries_total = Counter(
            "aigw_retries_total",
            "Upstream retry attempts",
            ["route", "backend"],
            registry=self.registry,
        )
        # SLO-aware admission control (ISSUE 8): requests shed with
        # 429 + Retry-After because every candidate replica's predicted
        # TTFT blew the configured SLO — load the gateway refused to
        # queue into collapse
        self.slo_sheds_total = Counter(
            "aigw_slo_sheds_total",
            "Requests shed because predicted TTFT exceeded the SLO on "
            "every candidate replica",
            ["route", "backend"],
            registry=self.registry,
        )
        # prefill/decode disaggregation: sessions the gateway moved from
        # a prefill-pressured replica to a decode-leaning one mid-stream
        self.migrations_total = Counter(
            "aigw_migrations_total",
            "Sessions migrated between replicas by the gateway",
            ["route", "backend"],
            registry=self.registry,
        )

    def export(self) -> bytes:
        return generate_latest(self.registry)


#: EngineStats attribute → Prometheus gauge name. One authoritative map
#: so tpuserve's /metrics, dashboards, and tests agree on the exported
#: serving-path surface. The /state twin of this contract is generated
#: in analysis/manifest.py (STATE_ONLY/METRICS_ONLY exemptions) and
#: enforced statically by the ``gauge-drift`` lint pass + the tier-1
#: drift smokes — adding an attr here without exporting it on /state
#: requires a METRICS_ONLY entry there. Including the adaptive decode window
#: (tpuserve_decode_window_steps: the K most recently dispatched, with
#: shrink/grow transition counters) and the phase breakdown
#: (prefill/transfer/emit milliseconds) behind TTFT regressions.
ENGINE_GAUGES: tuple[tuple[str, str], ...] = (
    ("active_slots", "tpuserve_active_slots"),
    ("queued", "tpuserve_queued_requests"),
    ("queue_wait_ms", "tpuserve_queue_wait_ms"),
    ("kv_pages_free", "tpuserve_kv_pages_free"),
    ("kv_occupancy", "tpuserve_kv_occupancy"),
    ("tokens_generated", "tpuserve_tokens_generated_total"),
    ("prefills", "tpuserve_prefills_total"),
    ("sp_prefills", "tpuserve_sp_prefills_total"),
    # long-context sp serving (sequence-sharded chunked prefill):
    # chunked-vs-monolithic routing volume and offset resumes on the
    # sp path (prefix-cache partial hits / migration continuations)
    ("sp_chunked_prefills", "tpuserve_sp_chunked_prefills_total"),
    ("sp_resume_prefills", "tpuserve_sp_resume_prefills_total"),
    ("sp_interactive_admits", "tpuserve_sp_interactive_admits_total"),
    ("chunked_prefill_steps", "tpuserve_chunked_prefill_steps_total"),
    ("decode_steps", "tpuserve_decode_steps_total"),
    ("decode_window", "tpuserve_decode_window_steps"),
    ("window_shrinks", "tpuserve_decode_window_shrinks_total"),
    ("window_grows", "tpuserve_decode_window_grows_total"),
    # speculative decoding (ISSUE 4): draft/accept volume, the
    # cumulative acceptance rate, the adaptive ladder's current
    # dispatch width and transition counters, the prefix-cache
    # continuation draft source, and the pipeline-draining full-rebuild
    # counter the zero-rebuild criterion asserts on
    ("spec_accepted", "tpuserve_spec_accepted_total"),
    ("spec_drafted", "tpuserve_spec_drafted_tokens_total"),
    ("spec_accept_rate", "tpuserve_spec_accept_rate"),
    ("spec_draft_len", "tpuserve_spec_draft_len"),
    ("spec_rung_ups", "tpuserve_spec_rung_ups_total"),
    ("spec_rung_downs", "tpuserve_spec_rung_downs_total"),
    ("spec_lookahead_slots", "tpuserve_spec_lookahead_slots_total"),
    ("state_rebuilds", "tpuserve_state_rebuilds_total"),
    ("prefix_cache_hits", "tpuserve_prefix_cache_hits_total"),
    ("prefix_tokens_reused", "tpuserve_prefix_tokens_reused_total"),
    # prefix-cache reuse surface (ISSUE 3): hit/miss/eviction counters,
    # the full-hit fast path (CoW'd final page + single-token resume),
    # and the residency/pinning gauges behind HBM capacity planning
    ("prefix_cache_misses", "tpuserve_prefix_cache_misses_total"),
    ("prefix_cache_evictions", "tpuserve_prefix_cache_evictions_total"),
    ("prefix_full_hits", "tpuserve_prefix_full_hits_total"),
    ("prefix_cow_copies", "tpuserve_prefix_cow_copies_total"),
    ("prefix_pages_resident", "tpuserve_prefix_pages_resident"),
    ("prefix_pages_pinned", "tpuserve_prefix_pages_pinned"),
    ("prefix_cache_hit_rate", "tpuserve_prefix_cache_hit_rate"),
    ("prefill_ms", "tpuserve_prefill_ms_total"),
    ("transfer_ms", "tpuserve_transfer_ms_total"),
    ("emit_ms", "tpuserve_emit_ms_total"),
    ("first_emit_ms", "tpuserve_first_emit_ms_total"),
    # prefill padding tax (ISSUE 6): real prompt tokens vs tokens the
    # padded program geometry processed — the per-replica observable
    # behind the ragged attention backend's padded_frac claim — plus
    # the warmup cost (collapsed compile surface = faster cold start)
    ("prefill_tokens_real", "tpuserve_prefill_tokens_real_total"),
    ("prefill_tokens_padded", "tpuserve_prefill_tokens_padded_total"),
    ("prefill_padded_frac", "tpuserve_prefill_padded_frac"),
    ("warmup_ms", "tpuserve_warmup_ms"),
    ("warm_programs", "tpuserve_warm_programs"),
    # XLA compile tracker (ISSUE 5, obs/xla_events.py): compiles seen
    # process-wide since the engine came up, and their total wall time —
    # a nonzero delta after warmup is a hot-path compile regression
    ("xla_compiles", "tpuserve_xla_compiles_total"),
    ("xla_compile_ms", "tpuserve_xla_compile_ms_total"),
    # adapter serving subsystem (ISSUE 7, tpuserve/adapters.py): hot
    # loads into the stacked LoRA rows, LRU evictions under row
    # pressure, resident adapters, and live slots decoding through a
    # non-base adapter row
    ("adapter_loads", "tpuserve_adapter_loads_total"),
    ("adapter_evictions", "tpuserve_adapter_evictions_total"),
    ("adapter_resident", "tpuserve_adapter_resident"),
    ("adapter_slots", "tpuserve_adapter_slots"),
    # prefill/decode disaggregation (ISSUE 8): sessions exported to /
    # imported from sibling replicas with the KV pages that traveled,
    # plus the live migration-eligibility gauge (prefill done, decode
    # young) the gateway's orchestrator polls
    ("migrations_out", "tpuserve_migrations_out_total"),
    ("migrations_in", "tpuserve_migrations_in_total"),
    ("migration_pages_out", "tpuserve_migration_pages_out_total"),
    ("migration_pages_in", "tpuserve_migration_pages_in_total"),
    ("migratable_slots", "tpuserve_migratable_slots"),
    # KV memory hierarchy (ISSUE 11, tpuserve/kvhost.py): host-spill-
    # tier churn (pages demoted on eviction / promoted back by prefix
    # hits / dropped by the host LRU budget), its live occupancy and
    # byte budget, and cross-replica /kv/pages fetch traffic in both
    # directions
    ("kv_spills", "tpuserve_kv_spills_total"),
    ("kv_revives", "tpuserve_kv_revives_total"),
    ("kv_spill_evictions", "tpuserve_kv_spill_evictions_total"),
    ("kv_spilled_pages", "tpuserve_kv_spilled_pages"),
    ("kv_spill_bytes", "tpuserve_kv_spill_bytes"),
    ("kv_host_bytes", "tpuserve_kv_host_bytes"),
    ("kv_fetches_out", "tpuserve_kv_fetches_out_total"),
    ("kv_fetches_in", "tpuserve_kv_fetches_in_total"),
    ("kv_fetch_pages_out", "tpuserve_kv_fetch_pages_out_total"),
    ("kv_fetch_pages_in", "tpuserve_kv_fetch_pages_in_total"),
    # multi-tenant fairness: distinct tenants holding decode slots, the
    # largest per-tenant in-flight count, and admissions the per-tenant
    # slot cap deferred (each deferral = one pass a request waited)
    ("tenants_active", "tpuserve_tenants_active"),
    ("tenant_max_slots", "tpuserve_tenant_max_slots"),
    ("tenant_deferrals", "tpuserve_tenant_deferrals_total"),
    # grammar-constrained decoding (ISSUE 9, tpuserve/constrain.py):
    # live constrained slots, requests admitted with a grammar, window
    # rollbacks at mask boundaries (the spec-rejection discipline),
    # device mask-row patches, and the compiled-grammar cache size
    ("constrained_slots", "tpuserve_constrained_slots"),
    ("constraint_requests", "tpuserve_constraint_requests_total"),
    ("constraint_rollbacks", "tpuserve_constraint_rollbacks_total"),
    ("constraint_mask_updates",
     "tpuserve_constraint_mask_updates_total"),
    ("constraint_grammars", "tpuserve_constraint_grammars"),
    # measured per-device memory (ISSUE 9 satellite): live jax
    # memory_stats() bytes (0 on backends without them) + the KV pool's
    # byte occupancy — the picker's first MEASURED memory signal
    ("device_bytes_in_use", "tpuserve_device_bytes_in_use"),
    ("device_bytes_limit", "tpuserve_device_bytes_limit"),
    ("device_memory_frac", "tpuserve_device_memory_frac"),
    ("kv_pool_bytes", "tpuserve_kv_pool_bytes"),
    ("kv_bytes_in_use", "tpuserve_kv_bytes_in_use"),
    # mesh serving (ISSUE 10): the engine's local device population,
    # the WORST per-device memory fraction (the picker's mesh memory
    # term — one hot shard stalls the whole tensor-parallel step), and
    # the analytical per-device ICI collective volume (bytes one
    # decoded token moves over the interconnect, and the running total)
    ("device_count", "tpuserve_device_count"),
    ("device_memory_frac_worst", "tpuserve_device_memory_frac_worst"),
    ("ici_bytes_per_token", "tpuserve_ici_bytes_per_token"),
    ("ici_bytes_total", "tpuserve_ici_bytes_total"),
    # quantized KV pages + fused decode (ISSUE 13): bits per stored KV
    # element (32/16 native, 8/4 quantized) and the all-layer HBM
    # bytes one cached token costs including its per-page scale share.
    # The RESOLVED decode rung itself is a string — it rides /metrics
    # as the labeled info gauge tpuserve_decode_attn_impl{impl=...}
    # (rendered by the server, not this numeric map) and /state as
    # decode_attn_impl/decode_attn_reason.
    ("kv_quant_bits", "tpuserve_kv_quant_bits"),
    ("kv_bytes_per_token", "tpuserve_kv_bytes_per_token"),
    # MoE serving (ISSUE 18, expert-parallel families): tokens the
    # router PLACED into expert capacity slots vs tokens DROPPED at the
    # capacity limit (both count padding rows — truthful to device
    # compute), the drop fraction, and the hottest-expert load ratio
    # (max expert tokens / mean — 1.0 is perfectly balanced). The
    # imbalance gauge is the picker's MoE pricing signal: PR 10
    # worst-device discipline, a replica is as fast as its hottest
    # expert. Constant 0 on dense families.
    ("moe_tokens_routed", "tpuserve_moe_tokens_routed_total"),
    ("moe_tokens_dropped", "tpuserve_moe_tokens_dropped_total"),
    ("moe_dropped_frac", "tpuserve_moe_dropped_frac"),
    ("moe_expert_imbalance", "tpuserve_moe_expert_imbalance"),
    # priority-tiered serving (ISSUE 19): the offline /v1/batches
    # class. Queued = never-shed backlog + host-parked preempted
    # sessions; active = decode slots it holds (≤ the batch_slot_frac
    # ceiling); preemptions/resumed = the park→resume churn interactive
    # arrivals drive; tokens = the idle-slot-soak volume the bench's
    # batch_tier A/B prices against measured idle capacity.
    ("batch_queued", "tpuserve_batch_queued"),
    ("batch_active", "tpuserve_batch_active"),
    ("batch_preemptions", "tpuserve_batch_preemptions_total"),
    ("batch_resumed", "tpuserve_batch_resumed_total"),
    ("batch_tokens", "tpuserve_batch_tokens_total"),
    # engine-truth usage metering (ISSUE 20): cumulative MeterRecord
    # totals. Every terminal stream (stop/length/cancelled/error — and
    # a migrated continuation exactly once for the spliced whole) emits
    # one record; these counters only move inside the engine's
    # _meter_emit funnel, so the gateway ledger's per-tenant sums
    # reconcile against them token-for-token. The page·byte·second
    # pair is the TPU-native residency dimension: KV bytes × seconds
    # occupied in HBM and in the host spill/park tier.
    ("meter_records", "tpuserve_meter_records_total"),
    ("meter_prefill_tokens", "tpuserve_meter_prefill_tokens_total"),
    ("meter_prefill_padded_tokens",
     "tpuserve_meter_prefill_padded_tokens_total"),
    ("meter_prefix_reused_tokens",
     "tpuserve_meter_prefix_reused_tokens_total"),
    ("meter_decode_tokens", "tpuserve_meter_decode_tokens_total"),
    ("meter_spec_drafted", "tpuserve_meter_spec_drafted_total"),
    ("meter_spec_accepted", "tpuserve_meter_spec_accepted_total"),
    ("meter_hbm_page_byte_s", "tpuserve_meter_hbm_page_byte_s_total"),
    ("meter_host_page_byte_s",
     "tpuserve_meter_host_page_byte_s_total"),
)

#: per-device gauge surface (ISSUE 10): key in one entry of
#: ``Engine.device_stats`` → labeled Prometheus gauge name. One
#: authoritative map, same drift-check contract as ENGINE_GAUGES —
#: every key here must appear in the engine's per-device dicts and
#: every gauge must render on /metrics with a ``device`` label.
DEVICE_GAUGES: tuple[tuple[str, str], ...] = (
    ("bytes_in_use", "tpuserve_device_bytes_in_use_per_device"),
    ("bytes_limit", "tpuserve_device_bytes_limit_per_device"),
    ("memory_frac", "tpuserve_device_memory_frac_per_device"),
    ("kv_pool_bytes", "tpuserve_device_kv_pool_bytes"),
    ("kv_bytes_in_use", "tpuserve_device_kv_bytes_in_use"),
    ("kv_occupancy", "tpuserve_device_kv_occupancy"),
    ("param_bytes", "tpuserve_device_param_bytes"),
)


def render_device_gauges(devices: list) -> bytes:
    """Per-device stats dicts → labeled Prometheus gauges (appended to
    tpuserve's /metrics next to the scalar engine gauges)."""
    lines = []
    for _key, name in DEVICE_GAUGES:
        lines.append(f"# TYPE {name} gauge")
    for dev in devices:
        label = dev.get("id", 0)
        for key, name in DEVICE_GAUGES:
            lines.append(f'{name}{{device="{label}"}} {dev.get(key, 0)}')
    return ("\n".join(lines) + "\n").encode() if lines else b""


def render_moe_gauges(expert_load: list, layer_drops: list) -> bytes:
    """MoE per-expert / per-layer accumulators → labeled Prometheus
    gauges (appended to tpuserve's /metrics on MoE families only;
    dense families contribute zero bytes). The /state twins are the
    ``moe_expert_load`` / ``moe_layer_drops`` list fields — same
    ordering, expert index = gauge label."""
    if not expert_load and not layer_drops:
        return b""
    lines = ["# TYPE tpuserve_moe_expert_load gauge"]
    for e, n in enumerate(expert_load):
        lines.append(f'tpuserve_moe_expert_load{{expert="{e}"}} {n}')
    lines.append("# TYPE tpuserve_moe_layer_drops gauge")
    for layer, n in enumerate(layer_drops):
        lines.append(f'tpuserve_moe_layer_drops{{layer="{layer}"}} {n}')
    return ("\n".join(lines) + "\n").encode()


#: fleet rollup surface (ISSUE 12): key in ``FleetState.rollup()`` →
#: aggregate gauge name on the gateway's ``GET /fleet/metrics``. One
#: authoritative map, same drift-check contract as ENGINE_GAUGES —
#: every key here must appear in the rollup dict and every gauge must
#: render on the federation scrape next to the replica-labeled
#: ``tpuserve_*`` re-exports.
FLEET_GAUGES: tuple[tuple[str, str], ...] = (
    ("replicas_total", "aigw_fleet_replicas_total"),
    ("replicas_up", "aigw_fleet_replicas_up"),
    ("replicas_degraded", "aigw_fleet_replicas_degraded"),
    ("replicas_draining", "aigw_fleet_replicas_draining"),
    ("replicas_down", "aigw_fleet_replicas_down"),
    ("slots_total", "aigw_fleet_slots_total"),
    ("slots_free", "aigw_fleet_slots_free"),
    ("queued_total", "aigw_fleet_queued_total"),
    ("kv_occupancy_worst", "aigw_fleet_kv_occupancy_worst"),
    ("kv_occupancy_mean", "aigw_fleet_kv_occupancy_mean"),
    ("device_memory_frac_worst",
     "aigw_fleet_device_memory_frac_worst"),
    ("kv_spills_total", "aigw_fleet_kv_spills_total"),
    ("kv_revives_total", "aigw_fleet_kv_revives_total"),
    ("kv_fetch_pages_in_total", "aigw_fleet_kv_fetch_pages_in_total"),
    ("kv_fetch_pages_out_total",
     "aigw_fleet_kv_fetch_pages_out_total"),
    ("migrations_in_total", "aigw_fleet_migrations_in_total"),
    ("migrations_out_total", "aigw_fleet_migrations_out_total"),
    ("adapters_resident", "aigw_fleet_adapters_resident"),
    # live SLO burn-rate monitor (obs/slomon.py): latest closed
    # window's fleet goodput/burn (-1 = no closed window yet) and the
    # K-consecutive-windows sustained-overshoot flag ROADMAP item 2's
    # autoscaler consumes
    ("slo_goodput", "aigw_fleet_slo_goodput"),
    ("slo_burn_rate", "aigw_fleet_slo_burn_rate"),
    ("slo_overshoot_sustained", "aigw_fleet_slo_overshoot_sustained"),
)


def render_fleet_gauges(rollup: dict, backend: str = "") -> bytes:
    """FleetState rollup dict → aigw_fleet_* Prometheus gauges,
    labeled by backend pool when the gateway serves more than one."""
    sel = f'{{backend="{backend}"}}' if backend else ""
    lines = []
    for key, name in FLEET_GAUGES:
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{sel} {rollup.get(key, 0)}")
    return ("\n".join(lines) + "\n").encode()


#: usage-metering ledger surface (ISSUE 20): key in
#: ``UsageLedger.snapshot()`` → gauge name on the gateway's
#: ``GET /metrics``. Same drift-check contract as FLEET_GAUGES —
#: every key here must appear as a literal in the ledger's snapshot()
#: dict (gateway/usage.py) and every gauge must render on the scrape.
USAGE_GAUGES: tuple[tuple[str, str], ...] = (
    ("records_total", "aigw_usage_records_total"),
    ("prefill_tokens_total", "aigw_usage_prefill_tokens_total"),
    ("prefill_padded_tokens_total",
     "aigw_usage_prefill_padded_tokens_total"),
    ("prefix_reused_tokens_total",
     "aigw_usage_prefix_reused_tokens_total"),
    ("decode_tokens_total", "aigw_usage_decode_tokens_total"),
    ("spec_drafted_total", "aigw_usage_spec_drafted_total"),
    ("spec_accepted_total", "aigw_usage_spec_accepted_total"),
    ("hbm_page_byte_s_total", "aigw_usage_hbm_page_byte_s_total"),
    ("host_page_byte_s_total", "aigw_usage_host_page_byte_s_total"),
    ("cost_total", "aigw_usage_cost_total"),
    ("tenants", "aigw_usage_tenants"),
    ("windows_closed_total", "aigw_usage_windows_closed_total"),
    ("journal_lines_total", "aigw_usage_journal_lines_total"),
    ("reconcile_mismatches_total",
     "aigw_usage_reconcile_mismatches_total"),
    ("over_budget_tenants", "aigw_usage_over_budget_tenants"),
    ("burn_sustained_tenants", "aigw_usage_burn_sustained_tenants"),
)


def render_usage_gauges(snapshot: dict) -> bytes:
    """UsageLedger snapshot dict → aigw_usage_* Prometheus gauges
    (appended to the gateway's /metrics scrape)."""
    lines = []
    for key, name in USAGE_GAUGES:
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {snapshot.get(key, 0)}")
    return ("\n".join(lines) + "\n").encode()


#: fleet control plane surface (ISSUE 14): key in
#: ``FleetController.gauge_values()`` → gauge name on the gateway's
#: ``GET /fleet/metrics``. Same drift-check contract as FLEET_GAUGES —
#: every key here must appear in the controller's gauge dict and every
#: gauge must render on the federation scrape when a controller is
#: attached to the pool.
CONTROLLER_GAUGES: tuple[tuple[str, str], ...] = (
    ("scale_outs", "aigw_ctl_scale_outs_total"),
    ("scale_ins", "aigw_ctl_scale_ins_total"),
    ("drains", "aigw_ctl_drains_total"),
    ("retires", "aigw_ctl_retires_total"),
    ("failovers", "aigw_ctl_failovers_total"),
    ("launch_failures", "aigw_ctl_launch_failures_total"),
    ("launches_in_flight", "aigw_ctl_launches_in_flight"),
    ("drains_in_progress", "aigw_ctl_drains_in_progress"),
    ("replicas_min", "aigw_ctl_replicas_min"),
    ("replicas_max", "aigw_ctl_replicas_max"),
    ("replicas_live", "aigw_ctl_replicas_live"),
    ("idle_streak", "aigw_ctl_idle_streak"),
)


def render_controller_gauges(values: dict, backend: str = "") -> bytes:
    """FleetController gauge dict → aigw_ctl_* Prometheus gauges."""
    sel = f'{{backend="{backend}"}}' if backend else ""
    lines = []
    for key, name in CONTROLLER_GAUGES:
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{sel} {values.get(key, 0)}")
    return ("\n".join(lines) + "\n").encode()


def render_engine_gauges(stats: object) -> bytes:
    """EngineStats → Prometheus text exposition (appended to the
    prometheus_client registry output on tpuserve's /metrics)."""
    lines = []
    for attr, name in ENGINE_GAUGES:
        value = getattr(stats, attr, 0)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {value}")
    return ("\n".join(lines) + "\n").encode()


#: serving-phase histogram surface (ISSUE 5): phase key → Prometheus
#: family name. The authoritative map — EnginePhases builds its
#: histograms from it, /metrics renders it, /state derives
#: phase_percentiles from it, and the tier-1 drift smoke asserts the two
#: sides agree — so a renamed phase can't silently drop a percentile.
#: Distinct from the ENGINE_GAUGES *_ms cumulative totals: these are
#: real per-observation distributions (p50/p95/p99 are readable).
ENGINE_HISTOGRAMS: tuple[tuple[str, str], ...] = (
    ("queue_wait", "tpuserve_queue_wait_hist_ms"),
    ("prefill", "tpuserve_prefill_hist_ms"),
    ("ttft", "tpuserve_ttft_hist_ms"),
    ("first_emit", "tpuserve_first_emit_hist_ms"),
    ("decode_per_token", "tpuserve_decode_per_token_hist_ms"),
    ("transfer", "tpuserve_transfer_hist_ms"),
)

#: histogram bucket upper bounds in milliseconds (+Inf implicit). Spans
#: sub-ms transfer fetches to multi-second queue waits.
PHASE_BUCKETS_MS: tuple[float, ...] = (
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0,
)


class PhaseHistogram:
    """Fixed-bucket latency histogram with per-bucket trace-id exemplars.

    Hand-rolled rather than prometheus_client because (a) the writer is
    the engine thread — observe() must be a couple of list/scalar ops,
    no label lookups or locks — and (b) classic prometheus_client text
    export drops exemplars; we render OpenMetrics-style exemplars on the
    bucket lines ourselves. int/float stores are GIL-atomic; readers
    (percentiles, render) tolerate a torn count by one observation.
    """

    __slots__ = ("name", "buckets", "counts", "total", "count",
                 "exemplars")

    def __init__(self, name: str,
                 buckets: tuple[float, ...] = PHASE_BUCKETS_MS):
        self.name = name
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # last = +Inf
        self.total = 0.0
        self.count = 0
        # bucket index → (trace_id, observed value) of the most recent
        # traced observation landing there
        self.exemplars: dict[int, tuple[str, float]] = {}

    def observe(self, ms: float, trace_id: str = "") -> None:
        i = bisect.bisect_left(self.buckets, ms)
        self.counts[i] += 1
        self.total += ms
        self.count += 1
        if trace_id:
            self.exemplars[i] = (trace_id, ms)

    def percentile(self, q: float) -> float:
        """q in (0, 1] → linear interpolation inside the target bucket.
        -1.0 when empty (distinguishable from a real 0ms)."""
        counts = list(self.counts)
        n = sum(counts)
        if n == 0:
            return -1.0
        target = q * n
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target:
                hi = (self.buckets[i] if i < len(self.buckets)
                      else self.buckets[-1] * 2)
                lo = self.buckets[i - 1] if i > 0 else 0.0
                if c == 0:
                    return hi
                frac = (target - (cum - c)) / c
                return lo + (hi - lo) * frac
        return self.buckets[-1] * 2

    def percentiles(self) -> dict[str, float]:
        return {
            "p50": round(self.percentile(0.50), 3),
            "p95": round(self.percentile(0.95), 3),
            "p99": round(self.percentile(0.99), 3),
        }

    def cumulative(self) -> dict[str, int]:
        """Cumulative bucket counts ``{le: count}`` (including +Inf) —
        the JSON twin of the /metrics bucket lines, exported on /state
        (``ttft_hist_buckets``) so the gateway's burn-rate monitor
        (obs/slomon.py) consumes the histogram straight off the poll it
        already makes, no second scrape."""
        out: dict[str, int] = {}
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            le = (f"{self.buckets[i]:g}" if i < len(self.buckets)
                  else "+Inf")
            out[le] = cum
        return out

    def render(self) -> str:
        """Prometheus histogram exposition; bucket lines carry
        OpenMetrics-style ``# {trace_id="…"} v`` exemplars when a traced
        request landed in the bucket."""
        lines = [f"# TYPE {self.name} histogram"]
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            le = (f"{self.buckets[i]:g}" if i < len(self.buckets)
                  else "+Inf")
            line = f'{self.name}_bucket{{le="{le}"}} {cum}'
            ex = self.exemplars.get(i)
            if ex is not None:
                line += f' # {{trace_id="{ex[0]}"}} {ex[1]:g}'
            lines.append(line)
        lines.append(f"{self.name}_sum {self.total:g}")
        lines.append(f"{self.name}_count {cum}")
        return "\n".join(lines) + "\n"


class EnginePhases:
    """The engine's serving-phase histogram set (one PhaseHistogram per
    ENGINE_HISTOGRAMS entry). Owned by the Engine; rendered on /metrics
    and summarized as p50/p95/p99 on /state."""

    def __init__(self) -> None:
        self.hists: dict[str, PhaseHistogram] = {
            key: PhaseHistogram(name) for key, name in ENGINE_HISTOGRAMS
        }

    def observe(self, phase: str, ms: float, trace_id: str = "") -> None:
        h = self.hists.get(phase)
        if h is not None:
            h.observe(ms, trace_id)

    def percentiles(self) -> dict[str, dict[str, float]]:
        return {key: h.percentiles() for key, h in self.hists.items()}

    def render(self) -> bytes:
        return "".join(h.render() for h in self.hists.values()).encode()


class MCPMetrics:
    """MCP proxy instruments (reference internal/metrics/mcp_metrics.go:
    ``mcp.request.duration`` / ``mcp.method.count`` /
    ``mcp.initialization.duration`` / ``mcp.capabilities.negotiated`` /
    ``mcp.progress.notifications``, with method/backend/status/error
    attributes). Lives in the gateway's shared registry — scraped via
    GenAIMetrics.export on /metrics."""

    def __init__(self, registry: CollectorRegistry):
        self.registry = registry
        self.method_total = Counter(
            "mcp_method_total",
            "JSON-RPC methods handled by the MCP proxy",
            ["mcp_method_name", "mcp_backend", "status"],
            registry=self.registry,
        )
        self.request_duration = Histogram(
            "mcp_request_duration_seconds",
            "MCP request handling duration",
            ["mcp_method_name"],
            registry=self.registry,
            buckets=_LATENCY_BUCKETS,
        )
        self.initialization_duration = Histogram(
            "mcp_initialization_duration_seconds",
            "MCP session initialization duration (backend fan-out)",
            [],
            registry=self.registry,
            buckets=_LATENCY_BUCKETS,
        )
        self.capabilities_negotiated = Counter(
            "mcp_capabilities_negotiated_total",
            "Capabilities negotiated at initialize",
            ["capability_type", "capability_side"],
            registry=self.registry,
        )
        self.progress_notifications = Counter(
            "mcp_progress_notifications_total",
            "Progress notifications routed through the proxy",
            [],
            registry=self.registry,
        )
        self.errors_total = Counter(
            "mcp_errors_total",
            "MCP errors by method and type",
            ["mcp_method_name", "error_type"],
            registry=self.registry,
        )


@dataclass
class RequestMetrics:
    """Per-request lifecycle recorder (reference metrics.Metrics interface,
    metrics.go:97-127: StartRequest/SetModel/RecordTokenUsage/…)."""

    metrics: GenAIMetrics
    operation: str = "chat"
    provider: str = ""
    request_model: str = ""
    response_model: str = ""
    start: float = field(default_factory=time.monotonic)
    first_token_at: float = 0.0
    last_token_at: float = 0.0
    tokens_seen: int = 0
    final_usage: TokenUsage = field(default_factory=TokenUsage)
    error_type: str = ""
    # enrichment surfaced to the structured access log (reference: Envoy
    # dynamic-metadata pipeline)
    costs: dict[str, int] = field(default_factory=dict)
    attempts: int = 0
    # the serving replica's per-request id (tpuserve's x-aigw-request-id
    # response header) — joins gateway access-log lines against the
    # replica's /debug/requests/{id} flight-recorder timeline
    upstream_request_id: str = ""
    # the routing decision's audit-ring entry (ISSUE 12, mutable — the
    # ring owner keeps updating it): the access log extracts the
    # compact outcome fields so log lines join the decision ring the
    # same way they join spans and flight timelines
    decision: dict = field(default_factory=dict)

    def _labels(self) -> list[str]:
        return [
            self.operation,
            self.provider,
            self.request_model,
            self.response_model or self.request_model,
        ]

    def record_tokens_emitted(self, n: int) -> None:
        """Called per streamed chunk with content tokens (TTFT/ITL gauges,
        recorded only for streaming — reference processor_impl.go:563)."""
        if n <= 0:
            return
        now = time.monotonic()
        if self.first_token_at == 0.0:
            self.first_token_at = now
            self.metrics.time_to_first_token.labels(*self._labels()).observe(
                now - self.start
            )
        elif self.tokens_seen:
            itl = (now - self.last_token_at) / n
            self.metrics.time_per_output_token.labels(*self._labels()).observe(itl)
        self.last_token_at = now
        self.tokens_seen += n

    def finish(self, usage: TokenUsage, error_type: str = "") -> None:
        self.final_usage = usage
        self.error_type = error_type
        labels = self._labels()
        for token_type, n in (
            ("input", usage.input_tokens),
            ("output", usage.output_tokens),
            ("total", usage.total_tokens),
            ("cached_input", usage.cached_input_tokens),
        ):
            if n:
                self.metrics.token_usage.labels(*labels, token_type).observe(n)
        self.metrics.request_duration.labels(*labels, error_type).observe(
            time.monotonic() - self.start
        )
