"""Live SLO burn-rate monitor — goodput accounting in the gateway.

PR 8 computed goodput-under-SLO only inside ``bench.py`` (cumulative
server-side TTFT histogram bucket deltas over a capture window). This
module generalizes that machinery into a LIVE monitor the gateway runs
against the histogram snapshots the endpoint picker already polls from
every replica's ``/state`` (``ttft_hist_buckets``):

- per sliding window of ``window_s`` seconds, the delta of the
  cumulative TTFT buckets gives ``served`` (requests finishing their
  TTFT in the window) and ``under`` (those landing in a bucket ≤ the
  SLO);
- ``goodput = under / served`` and the **error-budget burn rate**
  ``burn = (1 - goodput) / (1 - objective)`` — burn 1.0 means the
  replica consumes its error budget exactly as fast as the objective
  allows; burn > 1.0 means the budget is burning down;
- a **sustained-overshoot flag**: ``k_windows`` consecutive closed
  windows with burn > 1.0. This is the exact predicate ROADMAP item 2's
  autoscaler consumes ("the picker's own predicted-TTFT model sustained
  over the SLO") — computed from measured TTFTs, not predictions, so a
  mispredicting model can't silently scale the fleet.

Server-side by construction: requests the gateway shed with 429 never
reach a replica histogram, so a fully-shedding pool shows *empty*
windows (no served traffic), which clear the overshoot streak — the
shed volume itself is visible on ``aigw_slo_sheds_total``.

Counter resets (replica restart) make bucket deltas negative; the
monitor detects that, re-anchors, and skips the torn window instead of
reporting nonsense. Windows with no observations are skipped too (an
idle replica is not overshooting). Pure bookkeeping, no I/O.
"""

from __future__ import annotations

import collections
import re
import time
from typing import Any, Iterable

#: default TTFT SLO when the backend configures none (slo_ttft_ms = 0):
#: the monitor still reports goodput against something sane rather than
#: staying dark until an operator sets a budget
DEFAULT_SLO_MS = 500.0


def parse_hist_buckets(text: str, name: str) -> dict[str, int]:
    """Cumulative bucket counts of one Prometheus histogram family from
    /metrics exposition text: ``{le: cumulative_count}``. Tolerates the
    OpenMetrics exemplar suffix tpuserve renders on bucket lines AND
    extra labels (the fleet federation endpoint adds ``replica=...``):
    counts from multiple label sets sum per ``le`` — for a replica-
    labeled fleet scrape that sum IS the fleet histogram."""
    out: dict[str, int] = {}
    for m in re.finditer(
            rf'^{re.escape(name)}_bucket{{([^}}]*)}}\s+(\d+)',
            text, re.M):
        le = re.search(r'le="([^"]+)"', m.group(1))
        if le is None:
            continue
        out[le.group(1)] = out.get(le.group(1), 0) + int(m.group(2))
    return out


def under_slo_count(buckets: dict[str, int], slo_ms: float) -> int:
    """Cumulative count of observations in the largest bucket whose
    upper bound is ≤ the SLO — the ``under`` side of goodput."""
    best = -1.0
    val = 0
    for le, c in buckets.items():
        if le == "+Inf":
            continue
        f = float(le)
        if f <= slo_ms and f >= best:
            best, val = f, int(c)
    return val


def total_count(buckets: dict[str, int]) -> int:
    return int(buckets.get("+Inf", 0))


def sum_buckets(many: Iterable[dict]) -> dict[str, int]:
    """Per-le sum of several cumulative bucket dicts (fleet roll-up of
    per-replica histograms; valid because every replica renders the
    same PHASE_BUCKETS_MS ladder)."""
    out: dict[str, int] = {}
    for h in many:
        for le, c in (h or {}).items():
            out[le] = out.get(le, 0) + int(c)
    return out


class _KeyState:
    __slots__ = ("anchor_ts", "anchor", "windows", "over_streak")

    def __init__(self) -> None:
        self.anchor_ts: float | None = None
        self.anchor: dict[str, int] = {}
        # closed windows, oldest→newest, bounded
        self.windows: collections.deque = collections.deque(maxlen=16)
        self.over_streak = 0


class SLOMonitor:
    """Sliding-window goodput + burn rate per key (one key per replica,
    plus the caller's synthetic fleet key). Fed by the picker's poll
    loop via :meth:`observe`; read by ``/fleet/state`` and the fleet
    gauges via :meth:`snapshot`."""

    #: synthetic key the fleet-wide sum is observed under
    FLEET_KEY = "~fleet"

    def __init__(self, slo_ms: float = 0.0, objective: float = 0.95,
                 window_s: float = 30.0, k_windows: int = 3):
        if not 0.0 < objective < 1.0:
            raise ValueError(
                f"slo objective must be in (0, 1) (got {objective})")
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0 (got {window_s})")
        self.slo_ms = float(slo_ms) if slo_ms > 0 else DEFAULT_SLO_MS
        self.objective = objective
        self.window_s = float(window_s)
        self.k_windows = max(1, int(k_windows))
        self._keys: dict[str, _KeyState] = {}

    # -- write side -------------------------------------------------------
    def observe(self, key: str, cum_buckets: dict[str, int],
                ts: float | None = None) -> None:
        """One polled cumulative-bucket snapshot for ``key``. Closes the
        current window when it has aged past ``window_s``."""
        now = time.monotonic() if ts is None else ts
        st = self._keys.setdefault(key, _KeyState())
        if st.anchor_ts is None:
            st.anchor_ts, st.anchor = now, dict(cum_buckets)
            return
        if now - st.anchor_ts < self.window_s:
            return
        served = total_count(cum_buckets) - total_count(st.anchor)
        under = (under_slo_count(cum_buckets, self.slo_ms)
                 - under_slo_count(st.anchor, self.slo_ms))
        if served < 0 or under < 0 or under > served:
            # counter reset (replica restart) tore the delta: re-anchor
            # and skip the window rather than report garbage
            st.anchor_ts, st.anchor = now, dict(cum_buckets)
            return
        if served == 0:
            # idle window: no traffic is not an overshoot — clear the
            # streak (a sustained flag must mean sustained BAD service,
            # not stale history) and slide the anchor
            st.over_streak = 0
            st.anchor_ts, st.anchor = now, dict(cum_buckets)
            return
        goodput = under / served
        burn = (1.0 - goodput) / max(1e-9, 1.0 - self.objective)
        st.windows.append({
            "t0": round(st.anchor_ts, 3),
            "t1": round(now, 3),
            "served": served,
            "under_slo": under,
            "goodput": round(goodput, 4),
            "burn_rate": round(burn, 4),
        })
        st.over_streak = st.over_streak + 1 if burn > 1.0 else 0
        st.anchor_ts, st.anchor = now, dict(cum_buckets)

    def forget(self, key: str) -> None:
        """Drop a dead replica's window state (its counters restart from
        zero when it comes back)."""
        self._keys.pop(key, None)

    # -- read side --------------------------------------------------------
    def sustained(self, key: str) -> bool:
        """True when the last ``k_windows`` closed windows ALL burned
        error budget faster than the objective allows — the autoscale /
        health-degrade predicate."""
        st = self._keys.get(key)
        return st is not None and st.over_streak >= self.k_windows

    def snapshot(self, key: str) -> dict[str, Any]:
        """Current monitor view for one key: the latest closed window's
        goodput/burn (-1.0 = no closed window yet), recent windows, and
        the sustained flag."""
        st = self._keys.get(key)
        last = st.windows[-1] if st is not None and st.windows else None
        return {
            "slo_ms": self.slo_ms,
            "objective": self.objective,
            "window_s": self.window_s,
            "k_windows": self.k_windows,
            "goodput": last["goodput"] if last else -1.0,
            "burn_rate": last["burn_rate"] if last else -1.0,
            "over_budget_streak": st.over_streak if st is not None else 0,
            "sustained_overshoot": self.sustained(key),
            "windows": list(st.windows) if st is not None else [],
        }

    def keys(self) -> list[str]:
        return [k for k in self._keys if k != self.FLEET_KEY]
