"""Fused W8A16 matmul Pallas kernel for weight-streaming-bound decode.

Decode reads every weight once per step, so throughput is set by HBM
bytes moved. The XLA path (``models/quant.py`` + ``llama._w``)
dequantizes ``int8 → bf16 * scale`` as a fused producer of the matmul,
but the dequantized operand still round-trips through bf16 tiles ahead
of the MXU. This kernel streams the **int8** tile into VMEM, converts
in-register, runs the MXU on bf16, and applies the per-output-column
scale to the f32 accumulator — per-column scaling commutes with the
contraction, so the multiply happens on the [M, TILE_N] result instead
of the [K, TILE_N] weight (K/M ≈ 500× less scaling work, and the weight
never exists in bf16 anywhere).

Decode-shape oriented: M (batch) is small, K/N are the model matrices
(multiples of 128). Grid is over N tiles; the Pallas pipeline
double-buffers the weight-tile DMA automatically.

Numerics: ≈ the XLA path, slightly better — scale is applied in f32
after accumulation instead of being rounded into bf16 weights first.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# renamed TPUCompilerParams → CompilerParams across jax releases;
# accept either
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")

# int8 weight-tile byte budget per grid step; double-buffered by the
# pipeline, so ~2x this lives in VMEM (16MB/core) alongside x and out.
_TILE_BYTES = 2 * 1024 * 1024


def _pick_tile_n(k: int, n: int) -> int:
    for tile in (512, 384, 256, 128):
        if n % tile == 0 and k * tile <= 2 * _TILE_BYTES:
            return tile
    return 0


def _kernel(x_ref, q_ref, s_ref, o_ref):
    w = q_ref[:].astype(jnp.bfloat16)  # int8 → bf16 in VMEM/registers
    acc = jnp.dot(x_ref[:], w, preferred_element_type=jnp.float32)
    o_ref[:] = (acc * s_ref[:]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _w8a16_matmul(x, q, scale, interpret=False):
    m, k = x.shape
    _, n = q.shape
    tile_n = _pick_tile_n(k, n)
    grid = (n // tile_n,)
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((m, k), lambda j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tile_n), lambda j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, tile_n), lambda j: (0, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(x, q, scale)


def supported(m: int, k: int, n: int) -> bool:
    """Shapes this kernel accepts: decode-sized M, 128-aligned K/N with
    a dividing tile. Everything else falls back to the XLA path."""
    return (
        m <= 64
        and k % 128 == 0
        and _pick_tile_n(k, n) > 0
    )


def w8a16_matmul(x: jax.Array, q: jax.Array,
                 scale: jax.Array) -> jax.Array:
    """``x [M, K] bf16 @ dequant(q [K, N] int8, scale [1, N] f32)``.

    Caller guarantees ``supported(M, K, N)``. Runs interpreted off-TPU
    so CPU tests exercise the same code path."""
    from aigw_tpu.ops.pallas._compat import is_tpu_backend

    return _w8a16_matmul(x, q, scale.reshape(1, -1),
                         interpret=not is_tpu_backend())
