"""Pallas TPU kernel: paged-attention decode.

One query token per sequence attends over its paged KV cache (the decode
hot loop). Design (ragged-paged-attention style, PAPERS.md
arxiv 2604.15464 — implementation is original):

- Grid ``(B, P)`` — sequence-major, pages innermost. The page table is a
  **scalar-prefetch** argument, so each page's K/V block is DMA'd from the
  HBM pool straight to VMEM by the Pallas pipeline (auto double-buffered)
  using a *data-dependent* index map: block ``p`` of sequence ``b`` comes
  from pool row ``page_table[b, p]``.
- Online softmax across pages: running max / denominator / weighted
  accumulator live in VMEM scratch, carried across the page loop for a
  fixed sequence; the output tile is written on the last page.
- GQA: Q heads are grouped per KV head inside the kernel; K/V stay
  un-repeated in HBM (bandwidth is the decode bottleneck).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _decode_kernel(
    # scalar prefetch
    page_table_ref,  # [B * P] int32 — pool row per (b, p)
    lengths_ref,  # [B] int32 — attend length per sequence
    # blocks
    q_ref,  # [1, H, D]
    k_ref,  # [1, page, Hkv, D]  (pool row selected by index map)
    v_ref,  # [1, page, Hkv, D]
    o_ref,  # [1, H, D]
    # scratch
    m_ref,  # [H, 128] f32 running max (col 0 used)
    l_ref,  # [H, 128] f32 running denom (col 0 used)
    acc_ref,  # [H, D] f32 weighted accumulator
    *,
    page_size: int,
    n_pages: int,
):
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = lengths_ref[b]

    # number of valid tokens in this page
    page_start = p * page_size
    valid = jnp.clip(length - page_start, 0, page_size)

    @pl.when(valid > 0)
    def _attend():
        q = q_ref[0]  # [H, D]
        k = k_ref[0]  # [page, Hkv, D]
        v = v_ref[0]
        H, D = q.shape
        page, Hkv, _ = k.shape
        group = H // Hkv

        qg = q.reshape(Hkv, group, D).astype(jnp.float32)
        kf = k.astype(jnp.float32)
        # logits [Hkv, group, page]
        logits = jax.lax.dot_general(
            qg, kf,
            dimension_numbers=(((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        ) / math.sqrt(D)
        idx = jax.lax.broadcasted_iota(jnp.int32, (Hkv, group, page), 2)
        logits = jnp.where(idx < valid, logits, -1e30)
        logits = logits.reshape(H, page)

        m_prev = m_ref[:, 0:1]  # [H, 1]
        m_cur = jnp.max(logits, axis=1, keepdims=True)  # [H, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)  # rescale factor [H, 1]
        probs = jnp.exp(logits - m_new)  # [H, page]
        # zero out invalid columns (exp(-1e30 - m) underflows already)
        l_new = alpha * l_ref[:, 0:1] + jnp.sum(probs, axis=1, keepdims=True)

        vf = v.astype(jnp.float32)  # [page, Hkv, D]
        pg = probs.reshape(Hkv, group, page)
        # pv [Hkv, group, D]
        pv = jax.lax.dot_general(
            pg, vf,
            dimension_numbers=(((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(H, D)
        m_ref[:, 0:1] = m_new
        l_ref[:, 0:1] = l_new

    @pl.when(p == n_pages - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def paged_attention_decode(
    q: jax.Array,  # [B, H, D]
    k_pool: jax.Array,  # [n_slots, Hkv, D] flattened page pool
    v_pool: jax.Array,  # [n_slots, Hkv, D]
    page_table: jax.Array,  # [B, P] int32
    lengths: jax.Array,  # [B] int32
    *,
    page_size: int,
    interpret: bool = False,
) -> jax.Array:
    """Returns attention output [B, H, D] (same dtype as q)."""
    B, H, D = q.shape
    n_slots, Hkv, _ = k_pool.shape
    P = page_table.shape[1]
    # view the pool as pages for block indexing
    k_pages = k_pool.reshape(n_slots // page_size, page_size, Hkv, D)
    v_pages = v_pool.reshape(n_slots // page_size, page_size, Hkv, D)
    flat_pt = page_table.reshape(-1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, P),
        in_specs=[
            pl.BlockSpec(
                (1, H, D), lambda b, p, pt, ln: (b, 0, 0),
            ),
            pl.BlockSpec(
                (1, page_size, Hkv, D),
                lambda b, p, pt, ln: (pt[b * P + p], 0, 0, 0),
            ),
            pl.BlockSpec(
                (1, page_size, Hkv, D),
                lambda b, p, pt, ln: (pt[b * P + p], 0, 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, p, pt, ln: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel, page_size=page_size, n_pages=P
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        interpret=interpret,
    )(flat_pt, lengths, q, k_pages, v_pages)
