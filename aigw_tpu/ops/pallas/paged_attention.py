"""Pallas TPU kernel: paged-attention decode.

One query token per sequence attends over its paged KV cache (the decode
hot loop). Design (ragged-paged-attention style, PAPERS.md
arxiv 2604.15464 — implementation is original):

- Grid ``(B, Hkv, P)`` — sequence, KV head, then pages innermost. The page
  table is a **scalar-prefetch** argument, so each page's K/V block is
  DMA'd from the HBM pool straight to VMEM by the Pallas pipeline (auto
  double-buffered) using a *data-dependent* index map: page ``p`` of
  sequence ``b`` comes from pool row ``page_table[b, p]``.
- Online softmax across pages: running max / denominator / weighted
  accumulator live in VMEM scratch, carried across the page loop for a
  fixed (sequence, head); the output tile is written on the last page.
- GQA: each grid step processes the ``group = H // Hkv`` query heads that
  share one KV head, as plain 2D matmuls (Mosaic-friendly; K/V stay
  un-repeated in HBM since bandwidth is the decode bottleneck).
- **Ragged DMA skip** — the reason this beats the XLA gather path: the
  gather materializes the FULL padded window per layer regardless of how
  long each sequence actually is. Here the index map *clamps* page
  indices past a sequence's last valid page to the last valid page
  itself, so consecutive grid steps see an unchanged block index and the
  Pallas pipeline skips the re-fetch — HBM traffic scales with the
  tokens actually in the cache, not the padded window. (Compute for
  those steps is already masked by ``pl.when``; it was only the DMA that
  kept the old kernels at parity with XLA.)
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _flash_update(rows, q, k_h, v_h, mask, m_ref, l_ref, acc_ref):
    """One online-softmax step for a row block: fold this page's
    masked logits into the running (max, denom, accumulator) scratch.
    Shared by all three kernels (decode v1/v2 and the speculative
    verifier) — they differ only in row layout and mask construction."""
    D = q.shape[1]
    logits = jax.lax.dot_general(
        q, k_h,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) / math.sqrt(D)
    logits = jnp.where(mask, logits, -1e30)
    m_prev = m_ref[rows, 0:1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    probs = jnp.exp(logits - m_new)
    l_ref[rows, 0:1] = alpha * l_ref[rows, 0:1] + jnp.sum(
        probs, axis=1, keepdims=True
    )
    pv = jax.lax.dot_general(
        probs, v_h,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_ref[rows, :] = acc_ref[rows, :] * alpha + pv
    m_ref[rows, 0:1] = m_new


def _decode_kernel(
    # scalar prefetch
    page_table_ref,  # [B * P] int32 — pool page id per (b, p)
    lengths_ref,  # [B] int32 — attend length per sequence
    # blocks
    q_ref,  # [1, 1, group, D]
    k_ref,  # [page, D] (pool page row + head column selected by index map)
    v_ref,  # [page, D]
    o_ref,  # [1, 1, group, D]
    # scratch
    m_ref,  # [group, 128] f32 running max (col 0 used)
    l_ref,  # [group, 128] f32 running denom (col 0 used)
    acc_ref,  # [group, D] f32 weighted accumulator
    *,
    page_size: int,
    n_pages: int,
):
    b = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = lengths_ref[b]
    valid = jnp.clip(length - p * page_size, 0, page_size)

    @pl.when(valid > 0)
    def _attend():
        q = q_ref[0, 0].astype(jnp.float32)  # [group, D]
        k = k_ref[:].astype(jnp.float32)  # [page, D]
        v = v_ref[:].astype(jnp.float32)  # [page, D]
        group = q.shape[0]
        page = k.shape[0]
        mask = jax.lax.broadcasted_iota(
            jnp.int32, (group, page), 1) < valid
        _flash_update(slice(None), q, k, v, mask, m_ref, l_ref, acc_ref)

    @pl.when(p == n_pages - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def paged_attention_decode(
    q: jax.Array,  # [B, H, D]
    k_pool: jax.Array,  # [n_slots, Hkv, D] flattened page pool
    v_pool: jax.Array,  # [n_slots, Hkv, D]
    page_table: jax.Array,  # [B, P] int32
    lengths: jax.Array,  # [B] int32
    *,
    page_size: int,
    interpret: bool = False,
) -> jax.Array:
    """Returns attention output [B, H, D] (same dtype as q)."""
    B, H, D = q.shape
    n_slots, Hkv, _ = k_pool.shape
    P = page_table.shape[1]
    group = H // Hkv
    # views for block indexing: the pool flattens to 2D so a (page, D)
    # block can select [pool row = page id, column window = kv head] —
    # contiguous reshapes only, no data movement.
    q4 = q.reshape(B, Hkv, group, D)
    k2d = k_pool.reshape(n_slots, Hkv * D)
    v2d = v_pool.reshape(n_slots, Hkv * D)
    flat_pt = page_table.reshape(-1)

    def kv_index(b, h, p, pt, ln):
        # ragged DMA skip: pages past the sequence's last valid page map
        # to the last valid page — unchanged block index ⇒ no re-fetch
        last = jnp.maximum(ln[b] - 1, 0) // page_size
        return pt[b * P + jnp.minimum(p, last)], h

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, P),
        in_specs=[
            pl.BlockSpec(
                (1, 1, group, D), lambda b, h, p, pt, ln: (b, h, 0, 0),
            ),
            pl.BlockSpec((page_size, D), kv_index),
            pl.BlockSpec((page_size, D), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group, D), lambda b, h, p, pt, ln: (b, h, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel, page_size=page_size, n_pages=P
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, group, D), q.dtype),
        interpret=interpret,
    )(flat_pt, lengths, q4, k2d, v2d)
    return out.reshape(B, H, D)


def _decode_kernel_v2(
    page_table_ref,  # [B * P] int32
    lengths_ref,  # [B] int32
    q_ref,  # [1, H, D]
    k_ref,  # [page, Hkv * D] — one full pool page, all heads
    v_ref,  # [page, Hkv * D]
    o_ref,  # [1, H, D]
    m_ref,  # [H, 128] f32
    l_ref,  # [H, 128] f32
    acc_ref,  # [H, D] f32
    *,
    page_size: int,
    n_pages: int,
    n_kv_heads: int,
):
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = lengths_ref[b]
    valid = jnp.clip(length - p * page_size, 0, page_size)

    @pl.when(valid > 0)
    def _attend():
        H, D = q_ref.shape[1], q_ref.shape[2]
        page = k_ref.shape[0]
        group = H // n_kv_heads
        q = q_ref[0].astype(jnp.float32)  # [H, D]
        mask = jax.lax.broadcasted_iota(jnp.int32, (group, page), 1) < valid
        for h in range(n_kv_heads):  # static unroll: one 2D matmul pair/head
            rows = slice(h * group, (h + 1) * group)
            k_h = k_ref[:, h * D : (h + 1) * D].astype(jnp.float32)
            v_h = v_ref[:, h * D : (h + 1) * D].astype(jnp.float32)
            _flash_update(rows, q[rows], k_h, v_h, mask,
                          m_ref, l_ref, acc_ref)

    @pl.when(p == n_pages - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def paged_attention_decode_v2(
    q: jax.Array,  # [B, H, D]
    k_pool: jax.Array,  # [n_slots, Hkv, D]
    v_pool: jax.Array,
    page_table: jax.Array,  # [B, P]
    lengths: jax.Array,  # [B]
    *,
    page_size: int,
    interpret: bool = False,
) -> jax.Array:
    """Grid (B, P): one instance streams a full page (all KV heads) —
    fewer grid steps, bigger DMAs than v1."""
    B, H, D = q.shape
    n_slots, Hkv, _ = k_pool.shape
    P = page_table.shape[1]
    k2d = k_pool.reshape(n_slots, Hkv * D)
    v2d = v_pool.reshape(n_slots, Hkv * D)
    flat_pt = page_table.reshape(-1)

    def kv_index(b, p, pt, ln):
        # ragged DMA skip (see module docstring)
        last = jnp.maximum(ln[b] - 1, 0) // page_size
        return pt[b * P + jnp.minimum(p, last)], 0

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, P),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda b, p, pt, ln: (b, 0, 0)),
            pl.BlockSpec((page_size, Hkv * D), kv_index),
            pl.BlockSpec((page_size, Hkv * D), kv_index),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, p, pt, ln: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel_v2, page_size=page_size, n_pages=P, n_kv_heads=Hkv
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        interpret=interpret,
    )(flat_pt, lengths, q, k2d, v2d)


def _verify_kernel(
    page_table_ref,  # [B * P] int32
    positions_ref,  # [B] int32 — position of query 0; <= -S = slot off
    q_ref,  # [1, S, H, D]
    k_ref,  # [page, Hkv * D]
    v_ref,  # [page, Hkv * D]
    o_ref,  # [1, S, H, D]
    m_ref,  # [Hkv * S * group, 128] f32
    l_ref,  # [Hkv * S * group, 128] f32
    acc_ref,  # [Hkv * S * group, D] f32
    *,
    page_size: int,
    n_pages: int,
    n_kv_heads: int,
):
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    pos0 = positions_ref[b]
    S, H, D = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    group = H // n_kv_heads
    # last query sits at pos0 + S - 1; pages past it contribute nothing
    valid = jnp.clip(pos0 + S - p * page_size, 0, page_size)

    @pl.when(valid > 0)
    def _attend():
        page = k_ref.shape[0]
        # causal per query row: row r = s * group + g attends global key
        # j <= pos0 + s, with j = p * page_size + column
        col = jax.lax.broadcasted_iota(jnp.int32, (S * group, page), 1)
        row_s = jax.lax.broadcasted_iota(
            jnp.int32, (S * group, page), 0) // group
        mask = (p * page_size + col) <= (pos0 + row_s)
        for h in range(n_kv_heads):
            rows = slice(h * S * group, (h + 1) * S * group)
            q = q_ref[0, :, h * group:(h + 1) * group, :].reshape(
                S * group, D).astype(jnp.float32)
            k_h = k_ref[:, h * D:(h + 1) * D].astype(jnp.float32)
            v_h = v_ref[:, h * D:(h + 1) * D].astype(jnp.float32)
            _flash_update(rows, q, k_h, v_h, mask, m_ref, l_ref, acc_ref)

    @pl.when(p == n_pages - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, 0:1], 1e-30)
        out = acc_ref[:] / denom  # [Hkv * S * group, D]
        for h in range(n_kv_heads):
            rows = slice(h * S * group, (h + 1) * S * group)
            o_ref[0, :, h * group:(h + 1) * group, :] = (
                out[rows].reshape(S, group, D).astype(o_ref.dtype)
            )


def _ragged_prefill_kernel(
    # scalar prefetch
    cu_ref,  # [B + 1] int32 — packed-row offsets: seq b owns [cu[b], cu[b+1])
    start_ref,  # [B] int32 — absolute position of seq b's first packed token
    page_table_ref,  # [B * P] int32
    # blocks
    q_ref,  # [QB, H * D] — one block of the packed query stream
    k_ref,  # [page, Hkv * D] — pool page selected by index map
    v_ref,  # [page, Hkv * D]
    o_ref,  # [QB, H * D]
    # scratch
    m_ref,  # [Hkv * QB * group, 128] f32
    l_ref,  # [Hkv * QB * group, 128] f32
    acc_ref,  # [Hkv * QB * group, D] f32
    *,
    page_size: int,
    n_pages: int,
    n_kv_heads: int,
    head_dim: int,
    q_block: int,
):
    nq = pl.program_id(0)
    b = pl.program_id(1)
    p = pl.program_id(2)

    @pl.when((b == 0) & (p == 0))
    def _init_out():
        # rows owned by no sequence (tail padding) must read as zeros;
        # owned rows are overwritten at their sequence's finalize step
        o_ref[:] = jnp.zeros_like(o_ref)

    @pl.when(p == 0)
    def _init_scratch():
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    lo = cu_ref[b]
    hi = cu_ref[b + 1]
    base = nq * q_block
    own_lo = jnp.maximum(lo - base, 0)  # block-relative owned rows
    own_hi = jnp.minimum(hi - base, q_block)
    overlap = own_hi > own_lo
    # highest query position any owned row of this block reaches: pages
    # entirely past it contribute nothing (and their DMA is skipped by
    # the clamped index map)
    max_pos = start_ref[b] + jnp.minimum(hi, base + q_block) - 1 - lo
    grp = q_ref.shape[1] // (n_kv_heads * head_dim)

    @pl.when(overlap & (p * page_size <= max_pos))
    def _attend():
        D = head_dim
        QB = q_block
        page = k_ref.shape[0]
        rows = jax.lax.broadcasted_iota(
            jnp.int32, (QB * grp, page), 0) // grp  # block row r
        g_idx = base + rows  # global packed row
        owned = (g_idx >= lo) & (g_idx < hi)
        pos = start_ref[b] + g_idx - lo
        col = jax.lax.broadcasted_iota(jnp.int32, (QB * grp, page), 1)
        mask = owned & ((p * page_size + col) <= pos)
        for h in range(n_kv_heads):
            sl = slice(h * QB * grp, (h + 1) * QB * grp)
            q_h = q_ref[:, h * grp * D:(h + 1) * grp * D].astype(
                jnp.float32).reshape(QB * grp, D)
            k_h = k_ref[:, h * D:(h + 1) * D].astype(jnp.float32)
            v_h = v_ref[:, h * D:(h + 1) * D].astype(jnp.float32)
            _flash_update(sl, q_h, k_h, v_h, mask, m_ref, l_ref, acc_ref)

    @pl.when((p == n_pages - 1) & overlap)
    def _finalize():
        D = head_dim
        QB = q_block
        denom = jnp.maximum(l_ref[:, 0:1], 1e-30)
        out = acc_ref[:] / denom  # [Hkv * QB * grp, D]
        row1 = jax.lax.broadcasted_iota(jnp.int32, (QB, 1), 0)
        owned_rows = (row1 >= own_lo) & (row1 < own_hi)  # [QB, 1]
        # the o block is shared by every sequence this q block spans:
        # write only the rows seq b owns, preserve the rest
        for h in range(n_kv_heads):
            sl = slice(h * QB * grp, (h + 1) * QB * grp)
            cols = slice(h * grp * D, (h + 1) * grp * D)
            blk = out[sl].reshape(QB, grp * D).astype(o_ref.dtype)
            o_ref[:, cols] = jnp.where(owned_rows, blk, o_ref[:, cols])


@functools.partial(
    jax.jit, static_argnames=("page_size", "q_block", "interpret"))
def ragged_prefill_attention(
    q: jax.Array,  # [T, H, D] — PACKED variable-length query stream
    k_pool: jax.Array,  # [n_slots, Hkv, D]
    v_pool: jax.Array,
    page_table: jax.Array,  # [B, P] int32
    cu_seqlens: jax.Array,  # [B + 1] int32 packed-row offsets per sequence
    start_pos: jax.Array,  # [B] int32 absolute position of each first row
    *,
    page_size: int,
    q_block: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Ragged paged-attention prefill (PAPERS.md arxiv 2604.15464): ONE
    program for any batch geometry. The query stream packs every
    sequence's new tokens back to back (sequence b owns packed rows
    [cu_seqlens[b], cu_seqlens[b+1]), its first row sitting at absolute
    position start_pos[b] — nonzero for offset-resumed prefill: prefix-
    cache partial hits and chunked-prefill continuations), padded only
    to a multiple of ``q_block`` — compute scales with TOTAL tokens, not
    per-sequence buckets. Causal flash attention runs against the paged
    KV pool (prefix pages plus the freshly scattered chunk) with the
    same scalar-prefetch page table + ragged-DMA-skip machinery as the
    decode/verify kernels; grid (q-blocks, seqs, pages) revisits each
    query block per overlapping sequence, so a block spanning a sequence
    boundary is handled by masking rather than host-side alignment.
    Returns [T, H, D]."""
    T, H, D = q.shape
    n_slots, Hkv, _ = k_pool.shape
    B, P = page_table.shape
    grp = H // Hkv
    qb = min(q_block, T)
    if T % qb:
        raise ValueError(f"packed length {T} not a multiple of "
                         f"q_block {qb}")
    q2d = q.reshape(T, H * D)
    k2d = k_pool.reshape(n_slots, Hkv * D)
    v2d = v_pool.reshape(n_slots, Hkv * D)
    flat_pt = page_table.reshape(-1)

    def q_index(nq, b, p, cu, st, pt):
        return nq, 0

    def kv_index(nq, b, p, cu, st, pt):
        # ragged DMA skip: pages past the sequence's last attended page
        # clamp to it — unchanged block index ⇒ the pipeline skips the
        # re-fetch (see module docstring)
        last = jnp.maximum(st[b] + cu[b + 1] - cu[b] - 1, 0) // page_size
        return pt[b * P + jnp.minimum(p, last)], 0

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(T // qb, B, P),
        in_specs=[
            pl.BlockSpec((qb, H * D), q_index),
            pl.BlockSpec((page_size, Hkv * D), kv_index),
            pl.BlockSpec((page_size, Hkv * D), kv_index),
        ],
        out_specs=pl.BlockSpec((qb, H * D), q_index),
        scratch_shapes=[
            pltpu.VMEM((Hkv * qb * grp, 128), jnp.float32),
            pltpu.VMEM((Hkv * qb * grp, 128), jnp.float32),
            pltpu.VMEM((Hkv * qb * grp, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _ragged_prefill_kernel, page_size=page_size, n_pages=P,
        n_kv_heads=Hkv, head_dim=D, q_block=qb,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, H * D), q.dtype),
        interpret=interpret,
    )(cu_seqlens, start_pos, flat_pt, q2d, k2d, v2d)
    return out.reshape(T, H, D)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def paged_attention_verify(
    q: jax.Array,  # [B, S, H, D] — S speculative query positions
    k_pool: jax.Array,  # [n_slots, Hkv, D]
    v_pool: jax.Array,
    page_table: jax.Array,  # [B, P]
    positions: jax.Array,  # [B] int32 position of q[:, 0]; <= -S disables
    *,
    page_size: int,
    interpret: bool = False,
) -> jax.Array:
    """Multi-query variant for speculative decoding's verify step: S
    consecutive query positions per sequence (pending token + drafts)
    attend the paged cache under a per-query causal mask, with the same
    ragged DMA skip as the decode kernels. Returns [B, S, H, D]."""
    B, S, H, D = q.shape
    n_slots, Hkv, _ = k_pool.shape
    P = page_table.shape[1]
    k2d = k_pool.reshape(n_slots, Hkv * D)
    v2d = v_pool.reshape(n_slots, Hkv * D)
    flat_pt = page_table.reshape(-1)

    def kv_index(b, p, pt, pos):
        last = jnp.maximum(pos[b] + S - 1, 0) // page_size
        return pt[b * P + jnp.minimum(p, last)], 0

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, P),
        in_specs=[
            pl.BlockSpec((1, S, H, D), lambda b, p, pt, pos: (b, 0, 0, 0)),
            pl.BlockSpec((page_size, Hkv * D), kv_index),
            pl.BlockSpec((page_size, Hkv * D), kv_index),
        ],
        out_specs=pl.BlockSpec((1, S, H, D),
                               lambda b, p, pt, pos: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hkv * S * (H // Hkv), 128), jnp.float32),
            pltpu.VMEM((Hkv * S * (H // Hkv), 128), jnp.float32),
            pltpu.VMEM((Hkv * S * (H // Hkv), D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _verify_kernel, page_size=page_size, n_pages=P, n_kv_heads=Hkv
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, S, H, D), q.dtype),
        interpret=interpret,
    )(flat_pt, positions, q, k2d, v2d)
