"""Fused Pallas TPU decode step: RoPE + KV append + paged attention in
ONE kernel per dispatch, with optional int8/int4 KV pages dequantized
in-kernel against per-page scale blocks (models/kvq.py layout).

The chained decode path runs, per layer: rope (XLA) → K/V scatter
(XLA) → window gather → dense attention — four HBM round-trips of which
the padded-window gather is the largest. This kernel collapses them:

- Grid ``(B, P)`` — sequence, then pages innermost, exactly the
  ``paged_attention_decode_v2`` walk (scalar-prefetch page table,
  data-dependent index map, ragged DMA skip: pages past a sequence's
  last valid page clamp to it, so the Pallas pipeline skips the
  re-fetch and HBM traffic scales with real cache occupancy).
- **RoPE in-kernel**: per-dispatch interleaved cos/sin tables
  ``[B, D]`` are precomputed once outside (they depend only on the
  positions scalar vector); the rotation itself — the per-head FLOPs —
  runs in VMEM as ``x·cos + (x @ S)·sin`` where ``S`` is the constant
  pair-swap matrix (built from iotas; a [D, D] MXU matmul instead of a
  lane-strided shuffle, which Mosaic lays out poorly).
- **In-kernel append**: the new K/V row (quantized when the pool is
  int8/int4: symmetric absmax per head, the kvq.py recipe bit-for-bit)
  is written into its page through ``input_output_aliases`` on the pool
  buffers — the output block spec targets the append page, which for a
  mid-page append IS the final walk block already in VMEM, so the
  read-modify-write costs one extra block copy-out, not a scatter pass
  over HBM. A page-aligned append starts a fresh page (no prior rows to
  preserve). Inactive slots write a zero row into the pool's LAST page,
  which the engine reserves as a dump page no page table ever
  references (the Pallas output pipeline must write *somewhere*; the
  XLA paths get the same guarantee from OOB-drop scatters).
- **Attention**: online softmax over the walked pages (pool rows
  ``< position``) with the new token's K/V folded in-register at
  finalize — the attended value for the current token is exactly the
  quantize→dequantize round-trip later steps will read back from HBM,
  so a token's view of itself never drifts between steps.
- **In-kernel dequant**: quantized pages multiply by their scale
  column as they stream through VMEM — the packed layout never
  round-trips through HBM at full width.

Semantics match ``paged_decode_walk`` below (the XLA fused reference
the engine runs off-TPU and, under a mesh, per head-shard inside
shard_map): scatter-then-walk attends pool rows ``<= position`` where
row ``position`` holds the freshly appended (round-tripped) values —
identical numbers to walk-then-fold. Parity is asserted in
tests/test_pallas_ops.py at production shapes.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_QMAX = {"int8": 127.0, "int4": 7.0}


def _rope_tables(positions: jax.Array, head_dim: int,
                 rope_theta: float):
    """Interleaved cos/sin tables [B, D] for the kernel's in-VMEM
    rotation: column d carries angle(pos, d // 2)."""
    freqs = 1.0 / (rope_theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    full = jnp.repeat(freqs, 2)  # [D]
    ang = positions.astype(jnp.float32)[:, None] * full[None, :]
    return jnp.cos(ang), jnp.sin(ang)


def _swap_matrix(D: int):
    """Constant [D, D] pair-swap-with-sign matrix: (x @ S)[2i] =
    -x[2i+1], (x @ S)[2i+1] = x[2i] — the rotate-pairs half of
    interleaved RoPE as an MXU matmul."""
    r = jax.lax.broadcasted_iota(jnp.int32, (D, D), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (D, D), 1)
    up = ((c == r + 1) & (r % 2 == 0)).astype(jnp.float32)
    dn = ((c == r - 1) & (r % 2 == 1)).astype(jnp.float32)
    return up - dn


def _rope_rows(x: jax.Array, cos: jax.Array, sin: jax.Array):
    """Rotate rows [R, D] by the interleaved tables [D] (f32 in/out)."""
    S = _swap_matrix(x.shape[-1])
    rot = jax.lax.dot_general(
        x, S, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return x * cos[None, :] + rot * sin[None, :]


def _fused_kernel(
    # scalar prefetch
    pt_ref,  # [B * P] int32 — pool page id per (b, p)
    len_ref,  # [B] int32 — pool rows already written (= position)
    act_ref,  # [B] int32 — 1 when the slot decodes this step
    apg_ref,  # [B] int32 — pool page the new row lands in (dump page
    #           for inactive slots)
    arow_ref,  # [B] int32 — row within that page (position % page)
    # blocks
    q_ref,  # [1, H * D] unroped query
    kn_ref,  # [1, Hkv * D] unroped new key
    vn_ref,  # [1, Hkv * D] new value
    cos_ref,  # [1, D] f32
    sin_ref,  # [1, D] f32
    k_ref,  # [page, Hkv * D] pool page (walk index map)
    v_ref,  # [page, Hkv * D]
    *rest,  # [ks_ref, vs_ref,] o_ref, ko_ref, vo_ref[, kso_ref, vso_ref]
    #         + scratch m_ref, l_ref, acc_ref, qr_ref
    page_size: int,
    n_pages: int,
    n_kv_heads: int,
    head_dim: int,
    qmax: float,
):
    quant = qmax > 0.0
    if quant:
        (ks_ref, vs_ref, o_ref, ko_ref, vo_ref, kso_ref, vso_ref,
         m_ref, l_ref, acc_ref, qr_ref) = rest
    else:
        o_ref, ko_ref, vo_ref, m_ref, l_ref, acc_ref, qr_ref = rest
    b = pl.program_id(0)
    p = pl.program_id(1)
    D = head_dim
    H = q_ref.shape[1] // D
    grp = H // n_kv_heads

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)
        # rope q once per sequence; reused (pre-scaled) by every page
        # step and the finalize fold
        cos = cos_ref[0]
        sin = sin_ref[0]
        q = q_ref[0].astype(jnp.float32).reshape(H, D)
        # round through the model compute dtype exactly like the XLA
        # path (rope() returns x.dtype before attention reads it)
        qr = _rope_rows(q, cos, sin).astype(q_ref.dtype).astype(
            jnp.float32)
        qr_ref[:] = qr / math.sqrt(D)

    length = len_ref[b]
    valid = jnp.clip(length - p * page_size, 0, page_size)

    @pl.when(valid > 0)
    def _attend():
        page = k_ref.shape[0]
        mask = jax.lax.broadcasted_iota(
            jnp.int32, (grp, page), 1) < valid
        for h in range(n_kv_heads):
            rows = slice(h * grp, (h + 1) * grp)
            k_h = k_ref[:, h * D:(h + 1) * D].astype(jnp.float32)
            v_h = v_ref[:, h * D:(h + 1) * D].astype(jnp.float32)
            if quant:
                k_h = k_h * ks_ref[:, h:h + 1]
                v_h = v_h * vs_ref[:, h:h + 1]
            logits = jax.lax.dot_general(
                qr_ref[rows, :], k_h,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # qr is pre-scaled by 1/sqrt(D)
            logits = jnp.where(mask, logits, -1e30)
            m_prev = m_ref[rows, 0:1]
            m_new = jnp.maximum(
                m_prev, jnp.max(logits, axis=1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            probs = jnp.exp(logits - m_new)
            l_ref[rows, 0:1] = alpha * l_ref[rows, 0:1] + jnp.sum(
                probs, axis=1, keepdims=True)
            pv = jax.lax.dot_general(
                probs, v_h,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_ref[rows, :] = acc_ref[rows, :] * alpha + pv
            m_ref[rows, 0:1] = m_new

    @pl.when(p == n_pages - 1)
    def _finalize():
        is_act = act_ref[b] == 1
        arow = arow_ref[b]
        cos = cos_ref[0]
        sin = sin_ref[0]
        kn = _rope_rows(
            kn_ref[0].astype(jnp.float32).reshape(n_kv_heads, D),
            cos, sin).astype(kn_ref.dtype).astype(jnp.float32)  # [Hkv, D]
        vn = vn_ref[0].astype(jnp.float32).reshape(n_kv_heads, D)
        if quant:
            # the kvq.py recipe, bit-for-bit: symmetric absmax/head,
            # round-half-even, qmax-clipped
            k_amax = jnp.max(jnp.abs(kn), axis=1)
            v_amax = jnp.max(jnp.abs(vn), axis=1)
            k_s = jnp.where(k_amax > 0.0, k_amax / qmax, 1.0)
            v_s = jnp.where(v_amax > 0.0, v_amax / qmax, 1.0)
            kq = jnp.clip(jnp.round(kn / k_s[:, None]), -qmax, qmax)
            vq = jnp.clip(jnp.round(vn / v_s[:, None]), -qmax, qmax)
            # the value every later read dequantizes to — fold THAT
            k_eff = kq * k_s[:, None]
            v_eff = vq * v_s[:, None]
        else:
            # the bf16/f32 round-trip the chained scatter+gather pays
            k_eff = kn.astype(ko_ref.dtype).astype(jnp.float32)
            v_eff = vn.astype(vo_ref.dtype).astype(jnp.float32)

        @pl.when(is_act)
        def _fold_new_token():
            for h in range(n_kv_heads):
                rows = slice(h * grp, (h + 1) * grp)
                logit = jax.lax.dot_general(
                    qr_ref[rows, :], k_eff[h:h + 1, :],
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )  # [grp, 1]
                m_prev = m_ref[rows, 0:1]
                m_new = jnp.maximum(m_prev, logit)
                alpha = jnp.exp(m_prev - m_new)
                pnew = jnp.exp(logit - m_new)
                l_ref[rows, 0:1] = (alpha * l_ref[rows, 0:1] + pnew)
                acc_ref[rows, :] = (acc_ref[rows, :] * alpha
                                    + pnew * v_eff[h:h + 1, :])
                m_ref[rows, 0:1] = m_new

        denom = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).reshape(1, H * D)[0].astype(
            o_ref.dtype)

        # -- append: rewrite the target page with the new row ----------
        page = k_ref.shape[0]
        row_mask = jax.lax.broadcasted_iota(
            jnp.int32, (page, n_kv_heads * D), 0) == arow
        # page-aligned append starts a FRESH page (the walk never
        # fetched it — rows past the append are unwritten future
        # positions); mid-page appends extend the final walk block
        fresh = arow == 0
        base_k = jnp.where(fresh, jnp.zeros_like(k_ref), k_ref[:])
        base_v = jnp.where(fresh, jnp.zeros_like(v_ref), v_ref[:])
        if quant:
            new_k = kq.reshape(1, n_kv_heads * D).astype(ko_ref.dtype)
            new_v = vq.reshape(1, n_kv_heads * D).astype(vo_ref.dtype)
        else:
            new_k = kn.reshape(1, n_kv_heads * D).astype(ko_ref.dtype)
            new_v = vn.reshape(1, n_kv_heads * D).astype(vo_ref.dtype)
        zero_row = jnp.zeros_like(new_k)
        ko_ref[:] = jnp.where(
            row_mask, jnp.where(is_act, new_k, zero_row), base_k)
        vo_ref[:] = jnp.where(
            row_mask, jnp.where(is_act, new_v, zero_row), base_v)
        if quant:
            srow_mask = jax.lax.broadcasted_iota(
                jnp.int32, (page, n_kv_heads), 0) == arow
            base_ks = jnp.where(fresh, jnp.zeros_like(ks_ref),
                                ks_ref[:])
            base_vs = jnp.where(fresh, jnp.zeros_like(vs_ref),
                                vs_ref[:])
            kso_ref[:] = jnp.where(
                srow_mask,
                jnp.where(is_act, k_s[None, :], 0.0), base_ks)
            vso_ref[:] = jnp.where(
                srow_mask,
                jnp.where(is_act, v_s[None, :], 0.0), base_vs)


@functools.partial(
    jax.jit,
    static_argnames=("rope_theta", "page_size", "interpret"))
def fused_paged_decode(
    q: jax.Array,  # [B, H, D] UNROPED query
    k_new: jax.Array,  # [B, Hkv, D] UNROPED new key
    v_new: jax.Array,  # [B, Hkv, D] new value
    k_rows: jax.Array,  # [n_slots, Hkv, D] pool (native or int8/int4)
    v_rows: jax.Array,
    page_table: jax.Array,  # [B, P] int32
    positions: jax.Array,  # [B] int32 — position of the new token
    active: jax.Array,  # [B] bool
    k_scale: jax.Array | None = None,  # [n_slots, Hkv] f32 (quantized)
    v_scale: jax.Array | None = None,
    *,
    rope_theta: float,
    page_size: int,
    interpret: bool = False,
):
    """One fused decode dispatch. Returns ``(attn [B, H, D] in q's
    dtype, k_rows', v_rows'[, k_scale', v_scale'])`` — the pool leaves
    are updated IN the kernel (input_output_aliases) with the new row
    appended at ``positions``; inactive rows write a zero row into the
    pool's last page (the engine-reserved dump page)."""
    B, H, D = q.shape
    n_slots, Hkv, _ = k_rows.shape
    P = page_table.shape[1]
    quant = k_scale is not None
    qdt = str(k_rows.dtype)
    qmax = _QMAX.get(qdt, 0.0) if quant else 0.0

    lengths = jnp.where(active, positions, 0).astype(jnp.int32)
    act = active.astype(jnp.int32)
    dump_page = n_slots // page_size - 1
    app_idx = jnp.clip(positions // page_size, 0, P - 1)
    app_page = jnp.where(
        active,
        jnp.take_along_axis(page_table, app_idx[:, None], axis=1)[:, 0],
        dump_page).astype(jnp.int32)
    app_row = jnp.where(active, positions % page_size, 0).astype(
        jnp.int32)
    cos_t, sin_t = _rope_tables(positions, D, rope_theta)

    q2d = q.reshape(B, H * D)
    kn2d = k_new.reshape(B, Hkv * D)
    vn2d = v_new.reshape(B, Hkv * D)
    k2d = k_rows.reshape(n_slots, Hkv * D)
    v2d = v_rows.reshape(n_slots, Hkv * D)
    flat_pt = page_table.reshape(-1)

    def row_index(b, p, pt, ln, ac, apg, ar):
        return b, 0

    def kv_index(b, p, pt, ln, ac, apg, ar):
        # ragged DMA skip: pages past the last valid page clamp to it
        last = jnp.maximum(ln[b] - 1, 0) // page_size
        return pt[b * P + jnp.minimum(p, last)], 0

    def append_index(b, p, pt, ln, ac, apg, ar):
        return apg[b], 0

    in_specs = [
        pl.BlockSpec((1, H * D), row_index),
        pl.BlockSpec((1, Hkv * D), row_index),
        pl.BlockSpec((1, Hkv * D), row_index),
        pl.BlockSpec((1, D), row_index),
        pl.BlockSpec((1, D), row_index),
        pl.BlockSpec((page_size, Hkv * D), kv_index),
        pl.BlockSpec((page_size, Hkv * D), kv_index),
    ]
    inputs = [q2d, kn2d, vn2d, cos_t, sin_t, k2d, v2d]
    out_specs = [
        pl.BlockSpec((1, H * D), row_index),
        pl.BlockSpec((page_size, Hkv * D), append_index),
        pl.BlockSpec((page_size, Hkv * D), append_index),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((B, H * D), q.dtype),
        jax.ShapeDtypeStruct(k2d.shape, k2d.dtype),
        jax.ShapeDtypeStruct(v2d.shape, v2d.dtype),
    ]
    # alias indices count ALL flattened operands, scalar-prefetch args
    # included (5 scalars, then q/kn/vn/cos/sin at 5-9, pools at 10+)
    aliases = {10: 1, 11: 2}  # k2d → ko, v2d → vo
    if quant:
        in_specs += [
            pl.BlockSpec((page_size, Hkv), kv_index),
            pl.BlockSpec((page_size, Hkv), kv_index),
        ]
        inputs += [k_scale, v_scale]
        out_specs += [
            pl.BlockSpec((page_size, Hkv), append_index),
            pl.BlockSpec((page_size, Hkv), append_index),
        ]
        out_shape += [
            jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
            jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
        ]
        aliases[12] = 3  # k_scale → kso
        aliases[13] = 4  # v_scale → vso

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(B, P),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),  # roped q / sqrt(D)
        ],
    )
    kernel = functools.partial(
        _fused_kernel, page_size=page_size, n_pages=P,
        n_kv_heads=Hkv, head_dim=D, qmax=qmax,
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )(flat_pt, lengths, act, app_page, app_row, *inputs)
    attn = outs[0].reshape(B, H, D)
    k_out = outs[1].reshape(n_slots, Hkv, D)
    v_out = outs[2].reshape(n_slots, Hkv, D)
    if quant:
        return attn, k_out, v_out, outs[3], outs[4]
    return attn, k_out, v_out


def paged_decode_walk(
    q: jax.Array,  # [B, H, D] roped query
    k_rows: jax.Array,  # [n_slots, Hkv, D] pool (native or int8/int4)
    v_rows: jax.Array,
    page_table: jax.Array,  # [B, P] int32
    lengths: jax.Array,  # [B] int32 — rows to attend (incl. new token)
    *,
    page_size: int,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """XLA fused-decode reference: online-softmax paged attention,
    one page per loop step — the fused kernel's math with memory
    bounded at [B, page, Hkv, D] instead of the gather path's full
    padded [B, T] window. The new token's K/V are already scattered
    (``lengths`` includes them), so walk-then-read equals the kernel's
    walk-then-fold. Quantized pools dequantize at the read. Off-TPU
    this IS the serving path; on a mesh it runs per head-shard inside
    shard_map (paged_decode_walk_spmd). Returns [B, H, D] in q's
    dtype."""
    B, H, D = q.shape
    Hkv = k_rows.shape[1]
    grp = H // Hkv
    P = page_table.shape[1]
    qf = q.astype(jnp.float32).reshape(B, Hkv, grp, D) / math.sqrt(D)
    offs = jnp.arange(page_size, dtype=jnp.int32)

    def body(p, carry):
        m, l, acc = carry
        slots = page_table[:, p][:, None] * page_size + offs[None, :]
        k = k_rows[slots].astype(jnp.float32)  # [B, page, Hkv, D]
        v = v_rows[slots].astype(jnp.float32)
        if k_scale is not None:
            k = k * k_scale[slots][..., None]
            v = v * v_scale[slots][..., None]
        logits = jnp.einsum("bhgd,bshd->bhgs", qf, k)
        kpos = p * page_size + offs
        mask = kpos[None, :] < lengths[:, None]  # [B, page]
        logits = jnp.where(mask[:, None, None, :], logits, -1e30)
        m_new = jnp.maximum(m, logits.max(-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        probs = jnp.exp(logits - m_new)
        l_new = alpha * l + probs.sum(-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum("bhgs,bshd->bhgd", probs, v)
        return m_new, l_new, acc_new

    m0 = jnp.full((B, Hkv, grp, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((B, Hkv, grp, 1), jnp.float32)
    acc0 = jnp.zeros((B, Hkv, grp, D), jnp.float32)
    # traced upper bound — the XLA analogue of the ragged DMA skip
    p_hi = jnp.clip((jnp.max(lengths) - 1) // page_size + 1, 0, P)
    _, l, acc = jax.lax.fori_loop(0, p_hi, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(B, H, D).astype(q.dtype)


def paged_decode_walk_spmd(
    q, k_rows, v_rows, page_table, lengths, *, mesh, page_size,
    k_scale=None, v_scale=None, axis: str = "tp",
):
    """The fused walk under shard_map: each device walks ITS local
    head shard of the pool — per-device local reads, no GSPMD gather,
    no cross-device collective inside attention (the layer all-reduce
    after wo is unchanged). Requires H and Hkv divisible by the axis
    size (the resolution matrix guards this)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as Ps

    heads = Ps(None, axis, None)
    quant = k_scale is not None

    if quant:
        def local(q_, k_, v_, ks_, vs_, pt_, ln_):
            return paged_decode_walk(
                q_, k_, v_, pt_, ln_, page_size=page_size,
                k_scale=ks_, v_scale=vs_)

        in_specs = (heads, heads, heads, Ps(None, axis), Ps(None, axis),
                    Ps(None, None), Ps(None))
        args = (q, k_rows, v_rows, k_scale, v_scale, page_table,
                lengths)
    else:
        def local(q_, k_, v_, pt_, ln_):
            return paged_decode_walk(
                q_, k_, v_, pt_, ln_, page_size=page_size)

        in_specs = (heads, heads, heads, Ps(None, None), Ps(None))
        args = (q, k_rows, v_rows, page_table, lengths)
    return shard_map(local, mesh=mesh, in_specs=in_specs,
                     out_specs=heads, check_rep=False)(*args)
