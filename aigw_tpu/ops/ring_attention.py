"""Ring attention: context/sequence parallelism over the ``sp`` mesh axis.

For sequences whose KV exceeds one chip's HBM, the sequence dimension is
sharded across the mesh; each device computes attention of its **local Q
shard** against K/V blocks that rotate around the ring via
``lax.ppermute`` (ICI neighbor exchanges — the blockwise/ring-attention
construction; SURVEY.md §5 long-context, PAPERS.md). Online softmax
accumulates across ring steps, so no device ever materializes the full
sequence.

Communication cost: ``sp - 1`` neighbor hops of the local K/V block per
attention call, fully overlapped by XLA with the per-step matmuls. This is
the SPMD equivalent the reference's world has no analogue for (its gateway
never touches model internals) — first-class here per the north star.

An Ulysses-style alternative (all-to-all head-scatter, cheaper when
``n_heads ≥ sp``) shares the entry point via ``strategy="ulysses"``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from aigw_tpu.utils.shard_compat import shard_map_untyped_carry


def _ring_attention_local(
    q: jax.Array,  # [B, S_loc, H, D] — this device's query shard
    k: jax.Array,  # [B, S_loc, Hkv, D]
    v: jax.Array,  # [B, S_loc, Hkv, D]
    *,
    axis: str,
    causal: bool,
) -> jax.Array:
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    group = H // Hkv
    idx = jax.lax.axis_index(axis)
    n = jax.lax.psum(1, axis)
    scale = 1.0 / math.sqrt(D)

    q_pos = idx * S + jnp.arange(S)  # global positions of local queries
    qg = q.reshape(B, S, Hkv, group, D)

    def block_attend(kb, vb, src):
        """Logits of local q against block kb/vb originating on `src`."""
        k_pos = src * S + jnp.arange(S)
        logits = jnp.einsum(
            "bshgd,bthd->bhgst", qg, kb,
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]  # [S, S]
            logits = jnp.where(mask[None, None, None, :, :], logits, -1e30)
        return logits  # [B, Hkv, group, S, S]

    def step(carry, i):
        acc, m, l, kb, vb = carry
        src = (idx - i) % n  # who produced the block we currently hold
        logits = block_attend(kb, vb, src)
        m_cur = jnp.max(logits, axis=-1)  # [B, Hkv, group, S]
        m_new = jnp.maximum(m, m_cur)
        alpha = jnp.exp(m - m_new)
        probs = jnp.exp(logits - m_new[..., None])
        l_new = l * alpha + probs.sum(-1)
        pv = jnp.einsum("bhgst,bthd->bshgd", probs.astype(vb.dtype), vb)
        acc = acc * alpha.transpose(0, 3, 1, 2)[..., None] + pv
        # rotate the block to the next device on the ring
        perm = [(j, (j + 1) % n) for j in range(n)]
        kb = jax.lax.ppermute(kb, axis, perm)
        vb = jax.lax.ppermute(vb, axis, perm)
        return (acc, m_new, l_new, kb, vb), None

    # plain accumulators: the varying-manual-axes check that once
    # required pvary-tagging these is disabled at the shard_map call
    # (utils/shard_compat.py — the deprecated lax.pvary migration)
    acc0 = jnp.zeros((B, S, Hkv, group, D), jnp.float32)
    m0 = jnp.full((B, Hkv, group, S), -1e30, jnp.float32)
    l0 = jnp.zeros((B, Hkv, group, S), jnp.float32)
    (acc, m, l, _, _), _ = jax.lax.scan(
        step, (acc0, m0, l0, k, v), jnp.arange(n)
    )
    denom = jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2)[..., None]
    out = (acc / denom).astype(q.dtype)
    return out.reshape(B, S, H * D)


def _ring_prefix_attention_local(
    q: jax.Array,  # [B, S_loc, H, D] — this device's chunk-query shard
    k: jax.Array,  # [B, S_loc, Hkv, D] — chunk keys (in-register)
    v: jax.Array,  # [B, S_loc, Hkv, D]
    kc: jax.Array,  # [B, T_loc, Hkv, D] — cached-context window shard
    vc: jax.Array,  # [B, T_loc, Hkv, D]
    prefix_lens: jax.Array,  # [B] int32 — valid context tokens (global)
    *,
    axis: str,
) -> jax.Array:
    """Ring attention for a prompt CHUNK resuming at an arbitrary offset.

    Two ring passes share one unnormalized online-softmax carry
    (acc, m, l): first the chunk's own K/V blocks under a chunk-relative
    causal mask (the prefix offset cancels on both sides, so the plain
    ``q_pos >= k_pos`` mask of ``_ring_attention_local`` is exact), then
    the gathered context window under ``t_pos < prefix_lens`` (the
    chunk's freshly scattered keys sit at positions >= prefix_len, so
    the window pass never double-counts them). One normalization at the
    end — identical math to a single softmax over [context ++ chunk].

    The chunk pass runs FIRST: its step-0 block is the diagonal (every
    query attends at least itself), which seeds a finite running max
    so a fully masked context (``prefix_lens == 0``) contributes
    ``exp(-1e30 - m) == 0`` instead of poisoning the accumulator.
    """
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    group = H // Hkv
    T = kc.shape[1]  # local context block length (T_global / sp)
    idx = jax.lax.axis_index(axis)
    n = jax.lax.psum(1, axis)
    scale = 1.0 / math.sqrt(D)
    perm = [(j, (j + 1) % n) for j in range(n)]

    qg = q.reshape(B, S, Hkv, group, D)
    q_pos = idx * S + jnp.arange(S)  # chunk-relative query positions

    def merge(acc, m, l, logits, vb):
        """Online-softmax merge of one block into the running carry."""
        m_cur = jnp.max(logits, axis=-1)  # [B, Hkv, group, S]
        m_new = jnp.maximum(m, m_cur)
        alpha = jnp.exp(m - m_new)
        probs = jnp.exp(logits - m_new[..., None])
        l_new = l * alpha + probs.sum(-1)
        pv = jnp.einsum("bhgst,bthd->bshgd", probs.astype(vb.dtype), vb)
        acc = acc * alpha.transpose(0, 3, 1, 2)[..., None] + pv
        return acc, m_new, l_new

    def chunk_step(carry, i):
        acc, m, l, kb, vb = carry
        src = (idx - i) % n
        k_pos = src * S + jnp.arange(S)
        logits = jnp.einsum(
            "bshgd,bthd->bhgst", qg, kb,
            preferred_element_type=jnp.float32,
        ) * scale
        mask = q_pos[:, None] >= k_pos[None, :]  # [S, S]
        logits = jnp.where(mask[None, None, None, :, :], logits, -1e30)
        acc, m, l = merge(acc, m, l, logits, vb)
        kb = jax.lax.ppermute(kb, axis, perm)
        vb = jax.lax.ppermute(vb, axis, perm)
        return (acc, m, l, kb, vb), None

    def ctx_step(carry, i):
        acc, m, l, kb, vb = carry
        src = (idx - i) % n
        t_pos = src * T + jnp.arange(T)  # global window positions
        logits = jnp.einsum(
            "bshgd,bthd->bhgst", qg, kb,
            preferred_element_type=jnp.float32,
        ) * scale
        mask = t_pos[None, :] < prefix_lens[:, None]  # [B, T]
        logits = jnp.where(mask[:, None, None, None, :], logits, -1e30)
        acc, m, l = merge(acc, m, l, logits, vb)
        kb = jax.lax.ppermute(kb, axis, perm)
        vb = jax.lax.ppermute(vb, axis, perm)
        return (acc, m, l, kb, vb), None

    acc0 = jnp.zeros((B, S, Hkv, group, D), jnp.float32)
    m0 = jnp.full((B, Hkv, group, S), -1e30, jnp.float32)
    l0 = jnp.zeros((B, Hkv, group, S), jnp.float32)
    (acc, m, l, _, _), _ = jax.lax.scan(
        chunk_step, (acc0, m0, l0, k, v), jnp.arange(n)
    )
    (acc, m, l, _, _), _ = jax.lax.scan(
        ctx_step, (acc, m, l, kc, vc), jnp.arange(n)
    )
    denom = jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2)[..., None]
    out = (acc / denom).astype(q.dtype)
    return out.reshape(B, S, H * D)


def _ulysses_attention_local(
    q: jax.Array, k: jax.Array, v: jax.Array, *, axis: str, causal: bool
) -> jax.Array:
    """Ulysses: all-to-all so each device holds ALL positions for a slice
    of heads, attends locally, then all-to-alls back. Requires
    n_kv_heads % sp == 0."""
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    n = jax.lax.psum(1, axis)
    group = H // Hkv

    # scatter heads, gather sequence: [B, S, H, D] → [B, S*n, H/n, D]
    def head_scatter(x):
        heads = x.shape[2]
        x = x.reshape(B, S, n, heads // n, D)
        x = jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                               tiled=False)
        return x.reshape(B, S * n, heads // n, D)

    def head_gather(x, heads):
        x = x.reshape(B, n, S, heads // n, D)
        x = jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                               tiled=False)
        return x.reshape(B, S, heads, D)

    qh = head_scatter(q)  # [B, T, H/n, D]
    kh = head_scatter(k)
    vh = head_scatter(v)
    T = S * n
    scale = 1.0 / math.sqrt(D)
    hq = qh.shape[2]
    hkv = kh.shape[2]
    g = hq // hkv
    qg = qh.reshape(B, T, hkv, g, D)
    logits = jnp.einsum("bshgd,bthd->bhgst", qg, kh,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        pos = jnp.arange(T)
        mask = pos[:, None] >= pos[None, :]
        logits = jnp.where(mask[None, None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhgst,bthd->bshgd", probs.astype(vh.dtype), vh)
    out = out.reshape(B, T, hq, D)
    out = head_gather(out, H)
    return out.reshape(B, S, H * D)


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis", "causal", "strategy")
)
def ring_attention(
    q: jax.Array,  # [B, S, H, D] — S sharded over `axis`
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,  # [B, S, Hkv, D]
    *,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = True,
    strategy: str = "ring",  # "ring" | "ulysses"
) -> jax.Array:
    """Sequence-parallel attention; returns [B, S, H*D] sharded like q."""
    local = (
        _ring_attention_local if strategy == "ring"
        else _ulysses_attention_local
    )
    fn = shard_map_untyped_carry(
        functools.partial(local, axis=axis, causal=causal),
        mesh=mesh,
        in_specs=(
            P(None, axis, None, None),
            P(None, axis, None, None),
            P(None, axis, None, None),
        ),
        out_specs=P(None, axis, None),
    )
    return fn(q, k, v)


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def ring_attention_prefix(
    q: jax.Array,  # [B, S, H, D] — chunk queries; S sharded over `axis`
    k: jax.Array,  # [B, S, Hkv, D] — chunk keys (in-register)
    v: jax.Array,  # [B, S, Hkv, D]
    kc: jax.Array,  # [B, T, Hkv, D] — gathered page window; T sharded
    vc: jax.Array,  # [B, T, Hkv, D]
    prefix_lens: jax.Array,  # [B] int32 — cached tokens ahead of chunk
    *,
    mesh: Mesh,
    axis: str = "sp",
) -> jax.Array:
    """Sequence-parallel chunk attention with cached-prefix resume.

    Requires S % sp == 0 and T % sp == 0 (the engine's chunk rungs are
    rounded up to a multiple of the sp axis, and the page window is a
    whole number of pages with page_size % sp == 0). Ring strategy only:
    Ulysses would all-to-all the full window per layer, defeating the
    point of chunking. Returns [B, S, H*D] sharded like q.
    """
    fn = shard_map_untyped_carry(
        functools.partial(_ring_prefix_attention_local, axis=axis),
        mesh=mesh,
        in_specs=(
            P(None, axis, None, None),
            P(None, axis, None, None),
            P(None, axis, None, None),
            P(None, axis, None, None),
            P(None, axis, None, None),
            P(None),
        ),
        out_specs=P(None, axis, None),
    )
    return fn(q, k, v, kc, vc, prefix_lens)
