# Developer entry points (tests force the CPU fake-chip platform through
# tests/conftest.py; bench runs on the real TPU).

.PHONY: test test-fast native bench gateway-bench tpu-capture chaos docs dist clean lint

# aigw-check (ISSUE 15): the invariant lint suite — jit-surface
# registry, engine-thread discipline, async-blocking, determinism, and
# gauge/state drift — over the whole package. Exit 1 on any
# unsuppressed finding; tests/test_staticcheck.py runs the same gate
# in tier-1. See docs/development.md for the rule catalog.
lint:
	env JAX_PLATFORMS=cpu python tools/staticcheck.py

test: native
	python -m pytest tests/ -q

test-fast: native
	python -m pytest tests/ -q -x --ignore=tests/test_llama_model.py \
	  --ignore=tests/test_parallel.py --ignore=tests/test_mixtral.py \
	  --ignore=tests/test_ring_attention.py --ignore=tests/test_pipeline.py

native:
	$(MAKE) -C native

bench:
	python bench.py

gateway-bench:
	python benchmarks/gateway_overhead.py

# One-shot on-chip capture (tok/s/chip, measured MFU vs analytical,
# ICI measured vs priced) — run the first time the TPU tunnel is up;
# prints a TPU_CAPTURE {...} line and persists the JSON artifact.
tpu-capture:
	python tools/tpu_capture.py

# Fleet control plane chaos smoke (ISSUE 14): the non-slow half of the
# chaos matrix — controller predicates/hysteresis, drain routing,
# breaker unification, pre-first-byte failover — against stub replicas.
# The kill -9 / drain-retire rigs over real engines are the slow tier.
# AIGW_TSAN=1: the engine-thread sanitizer is asserted on under churn —
# a thread-discipline violation fails the chaos run loudly instead of
# corrupting streams silently (ISSUE 15).
chaos:
	env JAX_PLATFORMS=cpu AIGW_TSAN=1 python -m pytest tests/test_fleet_controller.py -q -m 'not slow' -p no:cacheprovider

docs:
	python docs/build_site.py

codegen:
	python -m aigw_tpu.config.clientgen

clean:
	$(MAKE) -C native clean

dist:
	pip wheel --no-deps --no-build-isolation -w dist/ .
	@ls -la dist/
