# Runtime image for the gateway and tpuserve (the reference ships a
# Dockerfile that pulls the Envoy binary; ours is self-contained).
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
      g++ make zlib1g-dev && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY aigw_tpu ./aigw_tpu
COPY native ./native
RUN pip install --no-cache-dir . && make -C native

# TPU runtime: install the libtpu-enabled jax build for your fleet, e.g.
#   pip install 'jax[tpu]' -f https://storage.googleapis.com/jax-releases/libtpu_releases.html

EXPOSE 1975 8011
ENTRYPOINT ["python", "-m", "aigw_tpu"]
CMD ["run", "/etc/aigw/config.yaml", "--host", "0.0.0.0"]
