"""Tokenizers + chat templating for tpuserve.

Two implementations behind one protocol:
- ``HFTokenizer`` wraps a local ``tokenizer.json`` (tokenizers library; no
  network) for real checkpoints.
- ``ByteTokenizer`` is the dependency-free fallback used by tiny-random
  models and tests (byte-level, vocab 256 + specials) — the fake-chip mode
  that replaces the reference's testupstream in our test pyramid
  (SURVEY.md §4 implication (b)).
"""

from __future__ import annotations

from typing import Any, Protocol


class Tokenizer(Protocol):
    bos_id: int
    eos_id: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: list[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes as tokens 0..255; BOS=256, EOS=257."""

    bos_id = 256
    eos_id = 257

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: list[int]) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode(
            "utf-8", errors="replace"
        )


class HFTokenizer:
    def __init__(self, path: str):
        from tokenizers import Tokenizer as _T

        self._t = _T.from_file(path)
        vocab = self._t.get_vocab()
        self.bos_id = vocab.get("<|begin_of_text|>", vocab.get("<s>", 0))
        # end-of-turn token by family: Llama-3 <|eot_id|>, ChatML (Qwen)
        # <|im_end|>, GPT-style <|endoftext|>, sentencepiece </s>
        for tok in ("<|eot_id|>", "<|im_end|>", "<|end_of_text|>",
                    "<|endoftext|>", "</s>"):
            if tok in vocab:
                self.eos_id = vocab[tok]
                break
        else:
            self.eos_id = 0

    def encode(self, text: str) -> list[int]:
        return self._t.encode(text, add_special_tokens=False).ids

    def decode(self, ids: list[int]) -> str:
        return self._t.decode(ids, skip_special_tokens=True)


def load_tokenizer(source: str) -> Tokenizer:
    if source == "byte":
        return ByteTokenizer()
    return HFTokenizer(source)


def apply_chat_template(
    messages: list[dict[str, Any]], tokenizer: Tokenizer,
    template: str = "llama3",
) -> list[int]:
    """Render an OpenAI-style message list to prompt tokens.

    ``template``: "llama3" (header-id layout), "chatml" (Qwen families),
    or the plain textual layout for the byte tokenizer. (Template strings
    are the public prompt formats of the respective model cards.)
    """
    from aigw_tpu.schemas.openai import message_content_text

    if isinstance(tokenizer, ByteTokenizer):
        parts = []
        for m in messages:
            parts.append(f"<{m.get('role', 'user')}>: "
                         f"{message_content_text(m.get('content'))}\n")
        parts.append("<assistant>: ")
        return tokenizer.encode("".join(parts))

    if template == "chatml":
        text = ""
        for m in messages:
            role = m.get("role", "user")
            content = message_content_text(m.get("content"))
            text += f"<|im_start|>{role}\n{content}<|im_end|>\n"
        text += "<|im_start|>assistant\n"
        return tokenizer.encode(text)

    text = "<|begin_of_text|>"
    for m in messages:
        role = m.get("role", "user")
        content = message_content_text(m.get("content"))
        text += (
            f"<|start_header_id|>{role}<|end_header_id|>\n\n{content}<|eot_id|>"
        )
    text += "<|start_header_id|>assistant<|end_header_id|>\n\n"
    return tokenizer.encode(text)


class StreamingDecoder:
    """Incremental detokenizer: emits only text that can no longer change.

    Token-by-token ``decode([tok])`` corrupts multi-byte UTF-8 characters
    and multi-token graphemes; re-decoding the FULL id list per token is
    O(n\u00b2) per stream and runs on the server's event loop. Instead only a
    sliding window is re-decoded (the ids since the last committed
    boundary): the emitted delta is ``decode(window + [tok])`` minus
    ``decode(window)``, and the window resets whenever its text is stable
    \u2014 so per-token cost is O(window), independent of generation length.
    Text ending in U+FFFD (a partial UTF-8 character or an un-mergeable
    token boundary) is held back until the continuation arrives.
    """

    def __init__(self, tokenizer: Tokenizer):
        self._t = tokenizer
        self._ids: list[int] = []
        # two lagging pointers: ids[:prefix] are fully emitted;
        # ids[prefix:read] is the context overlap whose text is
        # subtracted from each new decode so tokenizer boundary
        # artifacts (BPE merges, leading-space handling) cancel out
        self._prefix = 0
        self._read = 0

    def push(self, token_id: int) -> str:
        self._ids.append(token_id)
        new_text = self._t.decode(self._ids[self._prefix:])
        # A trailing U+FFFD is *probably* a partial UTF-8 char or an
        # unfinished merge \u2014 hold it back. But only for a bounded number
        # of tokens: a model legitimately emitting replacement chars (or
        # a stream of invalid bytes) must neither stall the client nor
        # regrow the decode window; real partial characters complete
        # within a few tokens.
        if new_text.endswith("\ufffd") and len(self._ids) - self._read < 8:
            return ""
        prefix_text = self._t.decode(self._ids[self._prefix: self._read])
        if len(new_text) <= len(prefix_text):
            return ""
        self._prefix = self._read
        self._read = len(self._ids)
        return new_text[len(prefix_text):]

    def flush(self) -> str:
        new_text = self._t.decode(self._ids[self._prefix:])
        prefix_text = self._t.decode(self._ids[self._prefix: self._read])
        self._prefix = self._read = len(self._ids)
        return new_text[len(prefix_text):]
