"""Attention ops: XLA reference implementations (models/llama.py) and
Pallas TPU kernels for the hot paths."""
