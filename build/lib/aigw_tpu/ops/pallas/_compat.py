"""Backend detection for Pallas kernels.

``jax.default_backend()`` returns the PLATFORM name, which for the
tunneled-TPU plugin is "axon", not "tpu" — comparing against "tpu" alone
would silently run kernels in interpret mode on real hardware. Decide by
inspecting the device itself (platform or device kind), once.
"""

from __future__ import annotations

import jax

_cache: bool | None = None


def is_tpu_backend() -> bool:
    global _cache
    if _cache is None:
        try:
            d = jax.devices()[0]
        except Exception:
            # transient runtime-init failure: do NOT cache — a later call
            # may succeed, and permanently answering False would silently
            # run kernels in interpret mode on real hardware
            return False
        _cache = (
            d.platform.lower() == "tpu"
            or "tpu" in getattr(d, "device_kind", "").lower()
        )
    return _cache
