"""Weight-only int8 quantization (W8A16).

Decode on TPU is weight-streaming-bound (every step reads every weight
from HBM); symmetric per-output-channel int8 halves that traffic while
activations stay bf16. Inside the jitted step the int8 block is converted
and scaled right at the matmul operand, which XLA fuses — HBM sees int8,
the MXU sees bf16.

Quantized params replace each matrix ``name`` with ``name.q`` (int8) and
``name.scale`` (f32, per output column; per row for the embedding since it
is consumed by row gather). Norms and biases stay bf16. The model code
resolves either representation through ``models.llama._w``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

#: weight-name suffixes eligible for int8 (matrices on the matmul path)
_MATRIX_KINDS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


@partial(jax.jit, static_argnames=("axis",))
def _quantize_matrix(w: jax.Array, axis: int) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 along ``axis`` (the preserved/output axis).

    Jitted so the f32 upcast fuses into the reduction and the rounding —
    eager dispatch would materialize a full f32 copy (2GB for an 8B
    embedding), which busts HBM when quantizing a 16GB bf16 model in
    place on a 16GB chip."""
    wf = w.astype(jnp.float32)
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    amax = jnp.max(jnp.abs(wf), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def quantize_params(
    params: dict[str, jax.Array], consume: bool = False
) -> dict[str, jax.Array]:
    """bf16 param dict → W8A16 dict (un-quantized leaves pass through).

    ``consume=True`` removes each bf16 tensor from ``params`` as soon as
    its int8 replacement is materialized, bounding peak HBM to
    bf16-model + one tensor instead of bf16 + int8 copies — required to
    quantize an 8B bf16 model in place on a 16GB chip.
    """
    out: dict[str, jax.Array] = {}
    for name in list(params):
        w = params.pop(name) if consume else params[name]
        kind = name.rsplit(".", 1)[-1]
        if kind in _MATRIX_KINDS and w.ndim >= 2:
            # output channels = last axis for [in, out] (and [E, in, out])
            q, scale = _quantize_matrix(w, axis=w.ndim - 1)
            out[name + ".q"] = q
            out[name + ".scale"] = scale
        elif name == "lm_head":
            q, scale = _quantize_matrix(w, axis=1)
            out["lm_head.q"] = q
            out["lm_head.scale"] = scale
        elif name == "embed":
            # consumed by row gather: per-row scales
            q, scale = _quantize_matrix(w, axis=0)
            out["embed.q"] = q
            out["embed.scale"] = scale
        else:
            out[name] = w
    return out


def is_quantized(params: dict[str, jax.Array]) -> bool:
    return any(k.endswith(".q") for k in params)
