"""Multi-LoRA serving: per-request low-rank adapters, batched.

Adapters live stacked on device — ``l{i}.{kind}.lora_a`` is
``[n_adapters, r, in]`` and ``…lora_b`` is ``[n_adapters, out, r]`` — and
every batch slot carries an adapter index, so ONE compiled program serves
any mix of adapters (the vLLM multi-LoRA idea, implemented for this
engine's [B]-slot decode geometry):

    delta = (x @ A[idx]ᵀ) @ B[idx]ᵀ      (two thin matmuls per target)

Row ``n_adapters`` (the last row) is the all-zeros "no adapter" row;
requests without an adapter point there, so base-model behavior is exact
(not merely approximate). The α/r scaling folds into A at load time.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class LoRAConfig:
    rank: int = 16
    alpha: float = 32.0
    # matmul targets by weight kind (classic attention-only default)
    targets: tuple[str, ...] = ("wq", "wv")


_DIMS = {
    "wq": lambda c: (c.dim, c.n_heads * c.head_dim),
    "wk": lambda c: (c.dim, c.n_kv_heads * c.head_dim),
    "wv": lambda c: (c.dim, c.n_kv_heads * c.head_dim),
    "wo": lambda c: (c.n_heads * c.head_dim, c.dim),
    "w_gate": lambda c: (c.dim, c.ffn_dim),
    "w_up": lambda c: (c.dim, c.ffn_dim),
    "w_down": lambda c: (c.ffn_dim, c.dim),
}


def init_lora_adapters(
    key: jax.Array,
    model_cfg,
    lora_cfg: LoRAConfig,
    n_adapters: int,
    dtype=jnp.bfloat16,
    random_b: bool = False,
) -> dict[str, jax.Array]:
    """Stacked adapter weights (+1 trailing all-zero row).

    B matrices init to zero (the LoRA convention — adapters start as
    no-ops); ``random_b`` fills them for tests that need visible deltas.
    """
    scale = lora_cfg.alpha / lora_cfg.rank
    out: dict[str, jax.Array] = {}
    keys = iter(jax.random.split(key, model_cfg.n_layers * len(_DIMS) * 2))
    rows = n_adapters + 1  # + zero row
    for i in range(model_cfg.n_layers):
        for kind in lora_cfg.targets:
            d_in, d_out = _DIMS[kind](model_cfg)
            a = (
                jax.random.normal(next(keys), (rows, lora_cfg.rank, d_in),
                                  jnp.float32)
                / math.sqrt(d_in) * scale
            )
            if random_b:
                b = jax.random.normal(next(keys),
                                      (rows, d_out, lora_cfg.rank),
                                      jnp.float32) / math.sqrt(lora_cfg.rank)
            else:
                b = jnp.zeros((rows, d_out, lora_cfg.rank), jnp.float32)
            # zero row: base-model passthrough
            a = a.at[n_adapters].set(0.0)
            b = b.at[n_adapters].set(0.0)
            out[f"l{i}.{kind}.lora_a"] = a.astype(dtype)
            out[f"l{i}.{kind}.lora_b"] = b.astype(dtype)
    return out


def lora_delta(
    lora: dict[str, jax.Array] | None,
    key: str,
    x: jax.Array,  # [B, S, in]
    idx: jax.Array | None,  # [B] int32 adapter row per slot
) -> jax.Array | None:
    """Per-slot adapter contribution for ``x @ W[key]``, or None."""
    if lora is None or idx is None:
        return None
    a = lora.get(key + ".lora_a")
    if a is None:
        return None
    b = lora[key + ".lora_b"]
    a_sel = a[idx]  # [B, r, in]
    b_sel = b[idx]  # [B, out, r]
    t = jnp.einsum("bsd,brd->bsr", x, a_sel)
    return jnp.einsum("bsr,bor->bso", t, b_sel)
