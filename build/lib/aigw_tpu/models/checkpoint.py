"""Checkpoint save/load/import.

The serving engine's only real state is model weights (SURVEY.md §5:
"tpuserve adds real state — model weights load (orbax-style sharded
checkpoint read), KV-cache is ephemeral"). Orbax handles sharded
save/restore; ``import_hf_checkpoint`` converts local HuggingFace
safetensors (Llama/Mixtral layouts) into our flat parameter dict — no
network involved.
"""

from __future__ import annotations

import logging
import os
import re
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)


def save_checkpoint(params: dict[str, jax.Array], path: str) -> None:
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, params)
    ckptr.wait_until_finished()
    logger.info("saved checkpoint to %s", path)


def restore_checkpoint(
    path: str, like: dict[str, jax.Array] | None = None
) -> dict[str, jax.Array]:
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if like is not None:
        shapes = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), like
        )
        return ckptr.restore(path, shapes)
    return ckptr.restore(path)


#: HF tensor name → our flat name (Llama/Mistral layout). Projections are
#: stored [out, in] in HF and transposed to our [in, out] convention.
_HF_MAP = [
    (re.compile(r"^model\.embed_tokens\.weight$"), "embed", False),
    (re.compile(r"^model\.norm\.weight$"), "norm_f", False),
    (re.compile(r"^lm_head\.weight$"), "lm_head", True),
    (re.compile(r"^model\.layers\.(\d+)\.input_layernorm\.weight$"),
     "l{}.attn_norm", False),
    (re.compile(r"^model\.layers\.(\d+)\.self_attn\.q_proj\.weight$"),
     "l{}.wq", True),
    (re.compile(r"^model\.layers\.(\d+)\.self_attn\.k_proj\.weight$"),
     "l{}.wk", True),
    (re.compile(r"^model\.layers\.(\d+)\.self_attn\.v_proj\.weight$"),
     "l{}.wv", True),
    (re.compile(r"^model\.layers\.(\d+)\.self_attn\.o_proj\.weight$"),
     "l{}.wo", True),
    # Qwen2 QKV biases
    (re.compile(r"^model\.layers\.(\d+)\.self_attn\.q_proj\.bias$"),
     "l{}.bq", False),
    (re.compile(r"^model\.layers\.(\d+)\.self_attn\.k_proj\.bias$"),
     "l{}.bk", False),
    (re.compile(r"^model\.layers\.(\d+)\.self_attn\.v_proj\.bias$"),
     "l{}.bv", False),
    (re.compile(r"^model\.layers\.(\d+)\.post_attention_layernorm\.weight$"),
     "l{}.mlp_norm", False),
    (re.compile(r"^model\.layers\.(\d+)\.mlp\.gate_proj\.weight$"),
     "l{}.w_gate", True),
    (re.compile(r"^model\.layers\.(\d+)\.mlp\.up_proj\.weight$"),
     "l{}.w_up", True),
    (re.compile(r"^model\.layers\.(\d+)\.mlp\.down_proj\.weight$"),
     "l{}.w_down", True),
    # Mixtral MoE layout: experts are stacked into [E, ...] after loading
    (re.compile(r"^model\.layers\.(\d+)\.block_sparse_moe\.gate\.weight$"),
     "l{}.gate", True),
    (re.compile(
        r"^model\.layers\.(\d+)\.block_sparse_moe\.experts\.(\d+)\.w1\.weight$"),
     "l{}.w_gate.__expert{}", True),
    (re.compile(
        r"^model\.layers\.(\d+)\.block_sparse_moe\.experts\.(\d+)\.w3\.weight$"),
     "l{}.w_up.__expert{}", True),
    (re.compile(
        r"^model\.layers\.(\d+)\.block_sparse_moe\.experts\.(\d+)\.w2\.weight$"),
     "l{}.w_down.__expert{}", True),
]


def import_hf_checkpoint(
    hf_dir: str, dtype: Any = jnp.bfloat16
) -> dict[str, jax.Array]:
    """Read local ``*.safetensors`` shards (Llama layout) → flat params."""
    from safetensors import safe_open

    files = sorted(
        os.path.join(hf_dir, f)
        for f in os.listdir(hf_dir)
        if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no .safetensors files in {hf_dir}")
    params: dict[str, jax.Array] = {}
    unmapped: list[str] = []
    for path in files:
        with safe_open(path, framework="np") as f:
            for name in f.keys():
                target = None
                transpose = False
                for pattern, fmt, tr in _HF_MAP:
                    m = pattern.match(name)
                    if m:
                        target = fmt.format(*m.groups())
                        transpose = tr
                        break
                if target is None:
                    unmapped.append(name)
                    continue
                arr = f.get_tensor(name)
                if transpose:
                    arr = arr.T
                params[target] = jnp.asarray(
                    np.ascontiguousarray(arr)
                ).astype(dtype)
    if unmapped:
        logger.warning("unmapped HF tensors ignored: %s", unmapped[:8])
    return _stack_experts(params)


def _stack_experts(params: dict[str, jax.Array]) -> dict[str, jax.Array]:
    """Collapse `l{i}.w_*.{__expertE}` staging keys into [E, ...] arrays
    (Mixtral's per-expert HF tensors → our stacked MoE layout)."""
    staged: dict[str, dict[int, jax.Array]] = {}
    out: dict[str, jax.Array] = {}
    for k, v in params.items():
        if ".__expert" in k:
            base, _, e = k.partition(".__expert")
            staged.setdefault(base, {})[int(e)] = v
        else:
            out[k] = v
    for base, experts in staged.items():
        out[base] = jnp.stack([experts[e] for e in sorted(experts)])
    return out
