"""Model families as pure functional JAX programs (bfloat16, static shapes,
jit-compiled once per shape bucket)."""

from aigw_tpu.models.registry import ModelSpec, get_model_spec, register_model

__all__ = ["ModelSpec", "get_model_spec", "register_model"]
