"""MCP (Model Context Protocol) proxy (reference internal/mcpproxy).

One client session multiplexed across N backend MCP servers over
streamable HTTP, with stateless-resumable encrypted composite session IDs,
aggregated/filtered tool listings, and prefix-routed tool calls.
"""

from aigw_tpu.mcp.proxy import MCPProxy, MCPBackend, MCPConfig

__all__ = ["MCPBackend", "MCPConfig", "MCPProxy"]
