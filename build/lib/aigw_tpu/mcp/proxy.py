"""MCP proxy core: JSON-RPC demux + session multiplexing + tool routing.

Parity with the reference (internal/mcpproxy/mcpproxy.go:59,
handlers.go:326-460):

- ``initialize``     — fan-out to every backend, compose the encrypted
  client session from per-backend session IDs
- ``tools/list``     — aggregate + filter, names prefixed ``backend__tool``
  (collision-free routing key, like the reference's tool→backend map)
- ``tools/call``     — strip the prefix, route to the owning backend with
  its own session ID
- ``prompts/list`` / ``resources/list`` / ``resources/templates/list`` —
  aggregated (prefixing names; URIs stay globally unique and unprefixed)
- ``resources/subscribe`` / ``unsubscribe`` — routed by URI ownership
- ``ping`` / ``notifications/*`` — handled locally / broadcast
- Reverse direction (reference handlers.go:983-1100): server→client
  requests (``roots/list``, ``sampling/createMessage``,
  ``elicitation/create``) arriving on a backend stream get their ``id``
  rewritten to a routable composite; the client's JSON-RPC *response*
  POSTed back is decoded and forwarded to the owning backend
  (handleClientToServerResponse, handlers.go:606-700). Server-issued
  ``_meta.progressToken`` values are rewritten the same way so client
  ``notifications/progress`` route back to the issuing backend
  (maybeUpdateProgressTokenMetadata / handlers.go:1752).
- GET listening stream: fans out GET streams to every backend in the
  session and relays their server-initiated traffic with proxy event
  ids, heartbeats, and gateway tool-change notifications (reference
  session.go streamNotifications).
- Streamable-HTTP: accepts JSON responses and single-event SSE replies
  from backends (spec 2025-06-18).
"""

from __future__ import annotations

import asyncio
import base64
import fnmatch
import os
import re
import json
import logging
import secrets
import time
from dataclasses import dataclass, field
from typing import Any

import aiohttp
from aiohttp import web

from aigw_tpu.mcp.crypto import SessionCrypto, SessionCryptoError

logger = logging.getLogger(__name__)

PROTOCOL_VERSION = "2025-06-18"
SESSION_HEADER = "mcp-session-id"
TOOL_SEP = "__"

# Server→client request ids and server-issued progress tokens are rewritten
# to carry the owning backend so the client's reply can be routed back
# (reference maybeServerToClientRequestModify encodes id+type+backend with a
# separator; we JSON-encode the original value, which preserves int/str
# distinction without per-type identifiers).
S2C_ID_PREFIX = "aigw-s2c."
PROGRESS_TOKEN_PREFIX = "aigw-pt."
# Gateway-initiated pings on the listening stream; client responses to
# these ids are swallowed (reference doNotForwardResponseToBackends).
PING_ID_PREFIX = "aigw-ping-"
# Server→client request methods that expect a client response routed back.
# ``ping`` is included so a backend-initiated ping's pong finds its way
# home (and int ids from different backends can't collide at the client).
S2C_REQUEST_METHODS = (
    "roots/list",
    "sampling/createMessage",
    "elicitation/create",
    "ping",
)


def _encode_routed(prefix: str, value: Any, backend: str) -> str:
    enc = (
        base64.urlsafe_b64encode(json.dumps(value).encode())
        .decode()
        .rstrip("=")
    )
    return f"{prefix}{enc}.{backend}"


def _decode_routed(prefix: str, s: Any) -> tuple[Any, str] | None:
    """Inverse of _encode_routed; None when ``s`` is not a routed value."""
    if not isinstance(s, str) or not s.startswith(prefix):
        return None
    enc, sep, backend = s[len(prefix):].partition(".")
    if not sep or not backend:
        return None
    try:
        value = json.loads(
            base64.urlsafe_b64decode(enc + "=" * (-len(enc) % 4))
        )
    except (ValueError, json.JSONDecodeError):
        return None
    return value, backend


@dataclass(frozen=True)
class MCPBackend:
    name: str
    url: str  # full MCP endpoint, e.g. http://host:port/mcp
    include_tools: tuple[str, ...] = ()  # glob patterns; empty = all
    exclude_tools: tuple[str, ...] = ()
    # regex patterns (reference MCPToolFilter includeRegex) — a tool is
    # included when it matches any glob OR any regex
    include_tools_regex: tuple[str, ...] = ()
    headers: tuple[tuple[str, str], ...] = ()

    def allows(self, tool: str) -> bool:
        if self.include_tools or self.include_tools_regex:
            globbed = any(
                fnmatch.fnmatch(tool, p) for p in self.include_tools)
            rex = any(
                re.fullmatch(p, tool) for p in self.include_tools_regex)
            if not globbed and not rex:
                return False
        return not any(fnmatch.fnmatch(tool, p) for p in self.exclude_tools)


@dataclass(frozen=True)
class MCPConfig:
    backends: tuple[MCPBackend, ...]
    path: str = "/mcp"
    # No constant default: an unset seed becomes a random per-process one
    # (sessions then don't survive restarts/replicas — set it explicitly in
    # production, as the reference requires via flags, mainlib/main.go:337).
    session_seed: str = ""
    session_fallback_seed: str = ""
    # Shared spool directory for Last-Event-Id replay buffers: set to a
    # volume all --workers processes / gateway replicas mount and stream
    # resumption survives reconnecting to a different replica
    # (mcp/replay.py FileReplayStore). Empty = in-memory, replica-local.
    replay_dir: str = ""

    # parsed MCPAuthzConfig | None (kept out of the frozen dataclass
    # equality on purpose — see parse())
    authorization: Any = None

    @staticmethod
    def parse(value: dict[str, Any]) -> "MCPConfig":
        backends = tuple(
            MCPBackend(
                name=b["name"],
                url=b["url"],
                include_tools=tuple(
                    (b.get("tool_filter") or {}).get("include", ())
                ),
                exclude_tools=tuple(
                    (b.get("tool_filter") or {}).get("exclude", ())
                ),
                include_tools_regex=tuple(
                    (b.get("tool_filter") or {}).get("include_regex", ())
                ),
                headers=tuple(
                    (str(h["name"]).lower(), str(h["value"]))
                    for h in b.get("headers", ())
                ),
            )
            for b in value.get("backends", ())
        )
        from aigw_tpu.mcp.authz import MCPAuthzConfig

        return MCPConfig(
            backends=backends,
            path=value.get("path", "/mcp"),
            # unset stays "" — MCPProxy generates a per-process random seed
            # once, so config hot-reloads don't invalidate live sessions
            session_seed=value.get("session_seed", ""),
            session_fallback_seed=value.get("session_fallback_seed", ""),
            replay_dir=value.get("replay_dir", ""),
            authorization=MCPAuthzConfig.parse(
                value.get("authorization")
            ),
        )


class _ReplayHandle:
    """Stream-lifetime view of a session's replay buffer.

    Re-resolves the underlying buffer whenever the proxy's store object
    changes (config hot-reload swapping ``replay_dir``), and pushes the
    store's blocking file I/O off the event loop — one slow flock on a
    shared volume must not stall every stream on the replica."""

    def __init__(self, proxy: "MCPProxy", token: str):
        self._proxy = proxy
        self._token = token
        self._store: Any = None
        self._buf: Any = None

    def _resolve(self):
        store = self._proxy._replay_store
        if store is not self._store:
            self._store = store
            self._buf = store.buffer(self._token)
        return self._buf

    async def append(self, encode) -> bytes:
        buf = self._resolve()
        if not self._store.blocking:
            # in-memory: inline on the loop — race-free (the loop is the
            # only writer) and no executor dispatch on the hot path
            return buf.append(encode)
        return await asyncio.to_thread(buf.append, encode)

    async def events_after(self, last_id: int) -> list[bytes]:
        buf = self._resolve()
        if not self._store.blocking:
            return buf.events_after(last_id)
        return await asyncio.to_thread(buf.events_after, last_id)


def _rpc_error(id_: Any, code: int, message: str) -> dict[str, Any]:
    return {"jsonrpc": "2.0", "id": id_,
            "error": {"code": code, "message": message}}


def _metric_error_type(status: int) -> str:
    """HTTP status → MCP error-type attribute (reference
    metrics.MCPErrorType values)."""
    return {
        400: "invalid_param",
        401: "unauthorized",
        403: "unauthorized",
        404: "invalid_session_id",
        413: "internal_error",
    }.get(status, "internal_error")


def _rpc_error_type(code: Any) -> str:
    """JSON-RPC error code → MCP error-type attribute (reference
    handlers.go errorType)."""
    return {
        -32601: "unsupported_method",
        -32602: "invalid_param",
        -32700: "invalid_json_rpc",
        -32600: "invalid_json_rpc",
        -32603: "internal_error",
        -32000: "invalid_session_id",
        -32001: "unauthorized",
    }.get(code, "internal_error")


class MCPProxy:
    def __init__(self, cfg: MCPConfig, metrics: Any = None):
        #: obs.metrics.MCPMetrics | None — method counts, durations,
        #: init/capability/progress instruments (reference
        #: internal/metrics/mcp_metrics.go)
        self.metrics = metrics
        self.cfg = cfg
        seed = cfg.session_seed
        if not seed:
            # AIGW_MCP_SESSION_SEED: process-group seed set by the
            # multi-worker launcher so SO_REUSEPORT workers can decrypt
            # each other's session tokens
            seed = os.environ.get("AIGW_MCP_SESSION_SEED", "")
        if not seed:
            seed = secrets.token_hex(32)
            if cfg.backends:
                logger.warning(
                    "mcp.session_seed not configured — using a random "
                    "per-process seed; sessions will not survive restarts "
                    "or span replicas"
                )
        self._seed = seed
        self._crypto = SessionCrypto(seed, cfg.session_fallback_seed)
        self._session: aiohttp.ClientSession | None = None
        self._authz = None
        if cfg.authorization is not None:
            from aigw_tpu.mcp.authz import JWTValidator

            self._authz = JWTValidator(cfg.authorization)
        # listening GET streams to wake when the tool topology changes
        # (reference toolChangeSignaler in streamNotifications)
        self._tool_change_listeners: set[asyncio.Event] = set()
        self._ping_seq = 0
        # bounded per-session replay buffers for Last-Event-Id resumption
        # (reference sse.go). The encrypted session itself stays
        # stateless; recent stream events live in the replay store —
        # in-memory (replica-local) by default, or a shared spool
        # directory when cfg.replay_dir is set (mcp/replay.py).
        from aigw_tpu.mcp.replay import make_store

        self._replay_store = make_store(cfg.replay_dir)

    def register(self, app: web.Application) -> None:
        app.router.add_post(self.cfg.path, self.handle)
        app.router.add_get(self.cfg.path, self.handle_get)
        app.router.add_delete(self.cfg.path, self.handle_delete)
        # registered unconditionally so authz can be enabled by a config
        # hot-reload after the router is frozen; 404 while authz is off
        app.router.add_get(
            "/.well-known/oauth-protected-resource",
            self._protected_resource_metadata,
        )
        app.on_cleanup.append(self._cleanup)

    def update_config(self, cfg: MCPConfig) -> None:
        """Hot-swap backends/filters/authz (reference: MCPConfig rides the
        same filterapi bundle watcher as routes). The HTTP path is fixed at
        registration time; live sessions survive unless the seed changes.
        Listening GET streams are woken with a tools/list_changed
        notification when the backend topology differs."""
        old = self.cfg
        self.cfg = cfg
        seed_changed = cfg.session_seed and cfg.session_seed != self._seed
        if (seed_changed
                or cfg.session_fallback_seed != old.session_fallback_seed):
            if seed_changed:
                self._seed = cfg.session_seed
            self._crypto = SessionCrypto(
                self._seed, cfg.session_fallback_seed
            )
        self._authz = None
        if cfg.authorization is not None:
            from aigw_tpu.mcp.authz import JWTValidator

            self._authz = JWTValidator(cfg.authorization)
        if old.replay_dir != cfg.replay_dir:
            from aigw_tpu.mcp.replay import make_store

            self._replay_store = make_store(cfg.replay_dir)
        if old.backends != cfg.backends:
            for ev in self._tool_change_listeners:
                ev.set()

    async def _protected_resource_metadata(self, _request) -> web.Response:
        """RFC 9728 protected-resource metadata (reference
        MCPRouteOAuth)."""
        if self._authz is None:
            return web.Response(status=404)
        cfg = self.cfg.authorization
        return web.json_response({
            "resource": cfg.resource or self.cfg.path,
            "authorization_servers": list(cfg.authorization_servers),
            "bearer_methods_supported": ["header"],
        })

    def _authenticate(self, request: web.Request) -> dict[str, Any] | None:
        """Returns verified claims, or None when authz is disabled."""
        if self._authz is None:
            return None
        from aigw_tpu.mcp.authz import AuthzError

        auth = request.headers.get("authorization", "")
        if not auth.lower().startswith("bearer "):
            raise AuthzError("missing bearer token")
        return self._authz.validate(auth[7:])

    async def _cleanup(self, _app) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=60)
            )
        return self._session

    # -- backend I/O ------------------------------------------------------
    async def _call_backend(
        self,
        backend: MCPBackend,
        payload: dict[str, Any],
        session_id: str = "",
    ) -> tuple[dict[str, Any] | None, str]:
        """POST one JSON-RPC message; returns (response-or-None, session id).

        Accepts direct JSON or a single-response SSE stream (both allowed
        by streamable HTTP)."""
        headers = {
            "content-type": "application/json",
            "accept": "application/json, text/event-stream",
            "mcp-protocol-version": PROTOCOL_VERSION,
        }
        headers.update(dict(backend.headers))
        if session_id:
            headers[SESSION_HEADER] = session_id
        http = await self._http()
        async with http.post(backend.url, json=payload,
                             headers=headers) as resp:
            new_session = resp.headers.get(SESSION_HEADER, session_id)
            if resp.status == 202:
                return None, new_session
            ctype = resp.headers.get("content-type", "")
            raw = await resp.read()
            if resp.status >= 400:
                raise RuntimeError(
                    f"backend {backend.name} returned {resp.status}: "
                    f"{raw[:200]!r}"
                )
            if "text/event-stream" in ctype:
                from aigw_tpu.translate.sse import SSEParser

                for ev in SSEParser().feed(raw) or []:
                    if not ev.data:
                        continue
                    msg = json.loads(ev.data)
                    if "result" in msg or "error" in msg:
                        return msg, new_session
                return None, new_session
            return (json.loads(raw) if raw else None), new_session

    def _replay_buffer(self, session_token: str):
        """Per-session replay handle with a shared id allocator (ids stay
        unique across concurrent streams on the session — and across
        replicas when the store is file-backed). Returns None without a
        session token. The handle re-resolves its buffer if a config
        hot-reload swaps the store, so live streams keep buffering into
        the store reconnects will consult; file I/O runs off the event
        loop."""
        if not session_token:
            return None
        return _ReplayHandle(self, session_token)

    async def handle_get(self, request: web.Request) -> web.StreamResponse:
        """GET /mcp with Last-Event-Id: replay buffered stream events
        after the given id (streamable-HTTP resumption), then close so the
        client re-opens a fresh listening stream. Without the header this
        is the listening stream (reference session.streamNotifications):
        a GET stream is opened to every backend in the session and their
        server-initiated traffic (notifications, elicitation/sampling/
        roots requests) is relayed with proxy event ids, periodic
        heartbeat pings, and a ``notifications/tools/list_changed`` event
        when a config reload changes the backend topology. Backends that
        answer GET with 405 (POST-only servers) are skipped; with zero
        live backend streams the response completes empty."""
        from aigw_tpu.mcp.authz import AuthzError

        token = request.headers.get(SESSION_HEADER, "")
        if not token:
            return web.Response(status=405)
        try:
            self._authenticate(request)
        except AuthzError as e:
            return web.Response(status=e.status)
        try:
            sessions = self._decode_session(token)
        except SessionCryptoError:
            return web.Response(status=404)
        last_header = request.headers.get("last-event-id", "")
        resp = web.StreamResponse(
            status=200,
            headers={"content-type": "text/event-stream",
                     "cache-control": "no-cache"},
        )
        await resp.prepare(request)
        if last_header:
            try:
                last = int(last_header)
            except ValueError:
                last = 0
            buf = self._replay_buffer(token)
            if buf is not None:
                for encoded in await buf.events_after(last):
                    await resp.write(encoded)
            await resp.write_eof()
            return resp
        await self._listen_streams(request, resp, token, sessions)
        return resp

    async def _listen_streams(
        self,
        request: web.Request,
        resp: web.StreamResponse,
        token: str,
        sessions: dict[str, str],
    ) -> None:
        from aigw_tpu.translate.sse import SSEEvent, SSEParser

        http = await self._http()
        queue: asyncio.Queue = asyncio.Queue()

        async def open_stream(b: MCPBackend):
            headers = {
                "accept": "text/event-stream",
                "mcp-protocol-version": PROTOCOL_VERSION,
                SESSION_HEADER: sessions[b.name],
                **dict(b.headers),
            }
            try:
                r = await http.get(
                    b.url, headers=headers,
                    timeout=aiohttp.ClientTimeout(total=None,
                                                  sock_connect=10),
                )
            except aiohttp.ClientError as e:
                logger.debug("mcp GET stream to %s failed: %s", b.name, e)
                return None
            if (r.status != 200
                    or "text/event-stream"
                    not in r.headers.get("content-type", "")):
                r.release()
                return None
            return b, r

        opened = await asyncio.gather(
            *(open_stream(b) for b in self.cfg.backends
              if sessions.get(b.name))
        )
        streams: list[tuple[MCPBackend, Any]] = [
            s for s in opened if s is not None
        ]
        if not streams:
            await resp.write_eof()
            return

        async def pump(b: MCPBackend, r) -> None:
            parser = SSEParser()
            try:
                async for chunk in r.content.iter_any():
                    for ev in parser.feed(chunk):
                        await queue.put((b.name, ev))
                for ev in parser.flush():
                    await queue.put((b.name, ev))
            except aiohttp.ClientError:
                pass
            finally:
                r.close()
                await queue.put(None)  # stream-ended sentinel

        pumps = [asyncio.ensure_future(pump(b, r)) for b, r in streams]
        change = asyncio.Event()
        self._tool_change_listeners.add(change)
        buf = self._replay_buffer(token)

        async def write_event(
            ev, backend_name: str | None = None, replayable: bool = True
        ) -> None:
            await resp.write(
                await self._prepare_relay_event(ev, backend_name, buf,
                                                replayable=replayable)
            )

        def ping_event():
            self._ping_seq += 1
            return SSEEvent(
                event="message",
                data=json.dumps({
                    "jsonrpc": "2.0",
                    "id": f"{PING_ID_PREFIX}{self._ping_seq}",
                    "method": "ping",
                }),
            )

        try:
            heartbeat = float(
                os.environ.get("MCP_PROXY_HEARTBEAT_INTERVAL", "60") or 0
            )
        except ValueError:
            heartbeat = 60.0
        live = len(pumps)
        getter: asyncio.Task | None = None
        changed: asyncio.Task | None = None
        try:
            # eager heartbeat: some clients block on the first event
            # (reference streamNotifications does the same)
            await write_event(ping_event(), replayable=False)
            while live > 0:
                if getter is None:
                    getter = asyncio.ensure_future(queue.get())
                if changed is None:
                    changed = asyncio.ensure_future(change.wait())
                done, _ = await asyncio.wait(
                    {getter, changed},
                    timeout=heartbeat if heartbeat > 0 else None,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if changed in done:
                    change.clear()
                    changed = None
                    await write_event(SSEEvent(
                        event="message",
                        data=json.dumps({
                            "jsonrpc": "2.0",
                            "method":
                                "notifications/tools/list_changed",
                        }),
                    ))
                if getter in done:
                    item = getter.result()
                    getter = None
                    if item is None:
                        live -= 1
                        continue
                    backend_name, ev = item
                    await write_event(ev, backend_name=backend_name)
                elif not done:
                    await write_event(ping_event(),
                                      replayable=False)  # heartbeat
        except (ConnectionResetError, aiohttp.ClientError,
                asyncio.CancelledError):
            pass  # client went away
        finally:
            self._tool_change_listeners.discard(change)
            for t in pumps:
                t.cancel()
            for t in (getter, changed):
                if t is not None:
                    t.cancel()
        try:
            await resp.write_eof()
        except ConnectionResetError:
            pass

    # -- session composition ---------------------------------------------
    def _encode_session(self, sessions: dict[str, str]) -> str:
        return self._crypto.encrypt(json.dumps(sessions).encode())

    def _decode_session(self, token: str) -> dict[str, str]:
        return json.loads(self._crypto.decrypt(token))

    # -- HTTP entry -------------------------------------------------------
    async def handle(self, request: web.Request) -> web.StreamResponse:
        if self.metrics is None:
            return await self._handle_post(request)
        t0 = time.monotonic()
        resp = await self._handle_post(request)
        method = request.get("mcp_method") or "unknown"
        # errors surface two ways: HTTP-level (4xx/5xx) and JSON-RPC
        # error envelopes riding HTTP 200 (unknown tool, backend
        # failure, internal error) — both must count as errors or a
        # total backend outage reads as "success" on the dashboard
        status = "success"
        err_type = ""
        if resp.status >= 400:
            status = "error"
            err_type = _metric_error_type(resp.status)
        else:
            body = getattr(resp, "body", None)
            if isinstance(body, (bytes, bytearray)) and b'"error"' in body:
                try:
                    parsed = json.loads(body)
                except ValueError:
                    parsed = None
                if isinstance(parsed, dict) and parsed.get("error"):
                    status = "error"
                    err_type = _rpc_error_type(
                        (parsed["error"] or {}).get("code"))
        self.metrics.method_total.labels(method, "", status).inc()
        self.metrics.request_duration.labels(method).observe(
            time.monotonic() - t0)
        if status == "error":
            self.metrics.errors_total.labels(method, err_type).inc()
        return resp

    async def _handle_post(
        self, request: web.Request
    ) -> web.StreamResponse:
        try:
            payload = json.loads(await request.read())
        except json.JSONDecodeError:
            return web.json_response(
                _rpc_error(None, -32700, "parse error"), status=400
            )
        if isinstance(payload, list):
            return web.json_response(
                _rpc_error(None, -32600, "batching not supported"),
                status=400,
            )
        method = payload.get("method", "")
        # surfaced to the metrics wrapper (client responses have no
        # method — they are the reverse leg of a server request)
        request["mcp_method"] = method or (
            "response" if "id" in payload else "")
        msg_id = payload.get("id")
        is_notification = msg_id is None

        from aigw_tpu.mcp.authz import AuthzError

        try:
            claims = self._authenticate(request)
        except AuthzError as e:
            resp = web.json_response(
                _rpc_error(msg_id, -32001, str(e)), status=e.status
            )
            if e.status == 401:
                resp.headers["www-authenticate"] = (
                    'Bearer resource_metadata='
                    '"/.well-known/oauth-protected-resource"'
                )
            return resp

        try:
            if method == "initialize":
                result, session = await self._initialize(payload)
                resp = web.json_response(result)
                resp.headers[SESSION_HEADER] = session
                return resp

            session_token = request.headers.get(SESSION_HEADER, "")
            try:
                sessions = (
                    self._decode_session(session_token)
                    if session_token
                    else {}
                )
            except SessionCryptoError as e:
                return web.json_response(
                    _rpc_error(msg_id, -32000, str(e)), status=404
                )

            if "method" not in payload:
                # JSON-RPC *response* from the client — the reverse leg of
                # a server→client request (reference
                # handleClientToServerResponse, handlers.go:606)
                if not session_token:
                    return web.json_response(
                        _rpc_error(None, -32600, "missing session ID"),
                        status=400,
                    )
                return await self._client_to_server_response(
                    payload, sessions
                )
            if method == "notifications/initialized":
                # already sent per-backend during the session fan-out
                return web.Response(status=202)
            if method == "notifications/cancelled":
                # broadcast best-effort: request ids are forwarded to
                # backends unmodified, so the owner recognizes its id and
                # aborts; others ignore it. (The reference 202s without
                # forwarding — handlers.go:490 TODO — this is strictly
                # more useful.)
                await self._broadcast(payload, sessions)
                return web.Response(status=202)
            if method == "notifications/progress":
                return await self._route_progress(payload, sessions)
            if is_notification:
                await self._broadcast(payload, sessions)
                return web.Response(status=202)
            if method == "ping":
                return web.json_response(
                    {"jsonrpc": "2.0", "id": msg_id, "result": {}}
                )
            if method == "tools/list":
                return web.json_response(
                    await self._tools_list(msg_id, sessions)
                )
            if method == "tools/call":
                if self._authz is not None:
                    full = (payload.get("params") or {}).get("name", "")
                    try:
                        self._authz.authorize_tool(full, claims or {})
                    except AuthzError as e:
                        return web.json_response(
                            _rpc_error(msg_id, -32001, str(e)),
                            status=e.status,
                        )
                return await self._tools_call_streaming(
                    request, payload, sessions
                )
            if method in ("prompts/list", "resources/list",
                          "resources/templates/list"):
                return web.json_response(
                    await self._aggregate_list(method, msg_id, sessions)
                )
            if method in ("prompts/get", "completion/complete"):
                return web.json_response(
                    await self._route_by_name(payload, sessions)
                )
            if method in ("resources/read", "resources/subscribe",
                          "resources/unsubscribe"):
                return web.json_response(
                    await self._route_resource(payload, sessions)
                )
            if method == "logging/setLevel":
                await self._broadcast(payload, sessions)
                return web.json_response(
                    {"jsonrpc": "2.0", "id": msg_id, "result": {}}
                )
            return web.json_response(
                _rpc_error(msg_id, -32601, f"method {method!r} not supported")
            )
        except Exception as e:
            logger.exception("mcp request failed")
            return web.json_response(
                _rpc_error(msg_id, -32603, f"internal error: {e}")
            )

    async def handle_delete(self, request: web.Request) -> web.Response:
        """Session teardown: best-effort DELETE to each backend."""
        token = request.headers.get(SESSION_HEADER, "")
        try:
            sessions = self._decode_session(token) if token else {}
        except SessionCryptoError:
            return web.Response(status=404)
        http = await self._http()
        for b in self.cfg.backends:
            sid = sessions.get(b.name)
            if not sid:
                continue
            try:
                await http.delete(
                    b.url, headers={SESSION_HEADER: sid,
                                    **dict(b.headers)}
                )
            except aiohttp.ClientError:
                pass
        return web.Response(status=200)

    # -- methods ----------------------------------------------------------
    async def _initialize(
        self, payload: dict[str, Any]
    ) -> tuple[dict[str, Any], str]:
        t0 = time.monotonic()

        async def init_one(b: MCPBackend):
            try:
                resp, session = await self._call_backend(b, payload)
                # spec: notify initialized after the response
                await self._call_backend(
                    b,
                    {"jsonrpc": "2.0",
                     "method": "notifications/initialized"},
                    session,
                )
                return b.name, session, resp
            except (aiohttp.ClientError, RuntimeError) as e:
                logger.warning("mcp backend %s init failed: %s", b.name, e)
                return b.name, "", None

        results = await asyncio.gather(
            *(init_one(b) for b in self.cfg.backends)
        )
        sessions = {name: sid for name, sid, _ in results if sid}
        if self.metrics is not None:
            self.metrics.initialization_duration.observe(
                time.monotonic() - t0)
            client_caps = (payload.get("params") or {}).get(
                "capabilities") or {}
            for cap in client_caps:
                self.metrics.capabilities_negotiated.labels(
                    str(cap), "client").inc()
            for _, _, resp in results:
                server_caps = ((resp or {}).get("result") or {}).get(
                    "capabilities") or {}
                for cap in server_caps:
                    self.metrics.capabilities_negotiated.labels(
                        str(cap), "server").inc()
        # listChanged: the proxy emits notifications/tools/list_changed on
        # config hot-reloads (see update_config)
        caps: dict[str, Any] = {"tools": {"listChanged": True}}
        result = {
            "jsonrpc": "2.0",
            "id": payload.get("id"),
            "result": {
                "protocolVersion": PROTOCOL_VERSION,
                "capabilities": caps,
                "serverInfo": {"name": "aigw-tpu-mcp", "version": "0.1.0"},
            },
        }
        return result, self._encode_session(sessions)

    async def _broadcast(
        self, payload: dict[str, Any], sessions: dict[str, str]
    ) -> None:
        await asyncio.gather(
            *(
                self._call_backend(b, payload, sessions.get(b.name, ""))
                for b in self.cfg.backends
                if sessions.get(b.name)
            ),
            return_exceptions=True,
        )

    async def _tools_list(
        self, msg_id: Any, sessions: dict[str, str]
    ) -> dict[str, Any]:
        async def list_one(b: MCPBackend):
            sid = sessions.get(b.name, "")
            if not sid:
                return []
            try:
                resp, _ = await self._call_backend(
                    b,
                    {"jsonrpc": "2.0", "id": msg_id, "method": "tools/list"},
                    sid,
                )
            except (aiohttp.ClientError, RuntimeError) as e:
                logger.warning("tools/list from %s failed: %s", b.name, e)
                return []
            tools = ((resp or {}).get("result") or {}).get("tools") or []
            out = []
            for t in tools:
                name = t.get("name", "")
                if not b.allows(name):
                    continue
                out.append(dict(t, name=f"{b.name}{TOOL_SEP}{name}"))
            return out

        lists = await asyncio.gather(
            *(list_one(b) for b in self.cfg.backends)
        )
        tools = [t for sub in lists for t in sub]
        return {"jsonrpc": "2.0", "id": msg_id, "result": {"tools": tools}}

    async def _tools_call_streaming(
        self,
        request: web.Request,
        payload: dict[str, Any],
        sessions: dict[str, str],
    ) -> web.StreamResponse:
        """tools/call with streamable-HTTP support: if the backend answers
        with an SSE stream (progress notifications before the result), the
        events are relayed to the client with monotonically increasing
        event ids (the resumption contract of spec 2025-06-18; reference
        mcpproxy/sse.go)."""
        msg_id = payload.get("id")
        params = payload.get("params") or {}
        full_name = params.get("name", "")
        backend_name, sep, tool = full_name.partition(TOOL_SEP)
        backend = next(
            (b for b in self.cfg.backends if b.name == backend_name), None
        )
        if not sep or backend is None:
            return web.json_response(
                _rpc_error(msg_id, -32602, f"unknown tool {full_name!r}")
            )
        if not backend.allows(tool):
            return web.json_response(
                _rpc_error(msg_id, -32602,
                           f"tool {full_name!r} is not allowed")
            )
        sid = sessions.get(backend.name, "")
        routed = dict(payload, params=dict(params, name=tool))

        headers = {
            "content-type": "application/json",
            "accept": "application/json, text/event-stream",
            "mcp-protocol-version": PROTOCOL_VERSION,
            **dict(backend.headers),
        }
        if sid:
            headers[SESSION_HEADER] = sid
        http = await self._http()
        async with http.post(backend.url, json=routed,
                             headers=headers) as resp:
            if self.metrics is not None:
                self.metrics.method_total.labels(
                    "tools/call", backend.name,
                    "success" if resp.status < 400 else "error",
                ).inc()
            ctype = resp.headers.get("content-type", "")
            if resp.status >= 400:
                raw = await resp.read()
                return web.json_response(
                    _rpc_error(msg_id, -32603,
                               f"backend {backend.name} returned "
                               f"{resp.status}: {raw[:200]!r}")
                )
            if "text/event-stream" not in ctype:
                raw = await resp.read()
                msg = json.loads(raw) if raw else None
                return web.json_response(
                    msg or _rpc_error(msg_id, -32603,
                                      "no response from backend")
                )
            # relay the stream with our own event ids
            from aigw_tpu.translate.sse import SSEParser

            out = web.StreamResponse(
                status=200,
                headers={"content-type": "text/event-stream",
                         "cache-control": "no-cache"},
            )
            await out.prepare(request)
            parser = SSEParser()
            buf = self._replay_buffer(
                request.headers.get(SESSION_HEADER, "")
            )

            async def relay(ev):
                # server→client requests riding the tools/call stream
                # (elicitation, sampling, roots) need routable ids
                await out.write(
                    await self._prepare_relay_event(ev, backend.name, buf)
                )

            async for chunk in resp.content.iter_any():
                for ev in parser.feed(chunk):
                    await relay(ev)
            for ev in parser.flush():
                await relay(ev)
            await out.write_eof()
            return out

    async def _tools_call(
        self, payload: dict[str, Any], sessions: dict[str, str]
    ) -> dict[str, Any]:
        msg_id = payload.get("id")
        params = payload.get("params") or {}
        full_name = params.get("name", "")
        backend_name, sep, tool = full_name.partition(TOOL_SEP)
        backend = next(
            (b for b in self.cfg.backends if b.name == backend_name), None
        )
        if not sep or backend is None:
            return _rpc_error(msg_id, -32602, f"unknown tool {full_name!r}")
        if not backend.allows(tool):
            return _rpc_error(
                msg_id, -32602, f"tool {full_name!r} is not allowed"
            )
        sid = sessions.get(backend.name, "")
        routed = dict(payload, params=dict(params, name=tool))
        resp, _ = await self._call_backend(backend, routed, sid)
        return resp or _rpc_error(msg_id, -32603, "no response from backend")

    async def _route_by_name(
        self, payload: dict[str, Any], sessions: dict[str, str]
    ) -> dict[str, Any]:
        """prompts/get + completion/complete: route by the
        ``backend__name`` prefix (same contract as tools/call)."""
        msg_id = payload.get("id")
        params = payload.get("params") or {}
        # completion/complete nests the name under ref.name; resource-
        # template refs carry ref.uri instead (URIs aren't prefixed —
        # route them like resources/read)
        name = params.get("name", "")
        ref = params.get("ref") or {}
        if not name and isinstance(ref, dict):
            name = ref.get("name", "")
            if not name and ref.get("uri"):
                return await self._route_resource(payload, sessions)
        backend_name, sep, bare = name.partition(TOOL_SEP)
        backend = next(
            (b for b in self.cfg.backends if b.name == backend_name), None
        )
        if not sep or backend is None:
            return _rpc_error(msg_id, -32602, f"unknown name {name!r}")
        routed_params = dict(params)
        if params.get("name"):
            routed_params["name"] = bare
        elif isinstance(ref, dict) and ref.get("name"):
            routed_params["ref"] = dict(ref, name=bare)
        routed = dict(payload, params=routed_params)
        resp, _ = await self._call_backend(
            backend, routed, sessions.get(backend.name, "")
        )
        return resp or _rpc_error(msg_id, -32603, "no response from backend")

    async def _route_resource(
        self, payload: dict[str, Any], sessions: dict[str, str]
    ) -> dict[str, Any]:
        """resources/read + subscribe/unsubscribe: route by URI.
        Aggregated resource listings are not renamed (URIs are globally
        unique), so try each backend that has a session until one answers
        without error. The reference instead prefixes URIs with the
        backend name (upstreamResourceURI); same routing power, but our
        unprefixed URIs also mean ``notifications/resources/updated``
        needs no URI rewrite on the way back to the client."""
        msg_id = payload.get("id")
        first_error: dict[str, Any] | None = None
        for b in self.cfg.backends:
            sid = sessions.get(b.name)
            if not sid:
                continue
            try:
                resp, _ = await self._call_backend(b, payload, sid)
            except (aiohttp.ClientError, RuntimeError):
                continue
            if resp is not None and "error" not in resp:
                return resp
            # keep the FIRST backend's error: with URI-owned resources the
            # owner answers first with a meaningful code; later backends'
            # generic not-found must not mask it
            if resp is not None and first_error is None:
                first_error = resp
        return first_error or _rpc_error(msg_id, -32602,
                                         "resource not found")

    # -- reverse direction (server→client requests) -----------------------
    async def _prepare_relay_event(
        self, ev, backend_name: str | None, buf,
        replayable: bool = True,
    ) -> bytes:
        """Shared relay path for backend stream events (tools/call SSE
        and the GET listening stream): rewrites server-initiated messages
        so replies can route back (``backend_name=None`` skips the
        rewrite — gateway-generated pings/tool-change events must keep
        their ids), then allocates a replayable proxy event id. Returns
        the encoded bytes to write."""
        if backend_name is not None and ev.data:
            try:
                msg = json.loads(ev.data)
            except ValueError:
                msg = None
            if isinstance(msg, dict) and msg.get("method"):
                modified = self._modify_server_message(msg, backend_name)
                if modified is not msg:
                    ev.data = json.dumps(modified)
        # heartbeats are written without ids and never buffered — they
        # must not evict resumable events from the bounded replay buffer
        # or advance Last-Event-Id
        if replayable and buf is not None:
            def encode_with_id(event_id: int) -> bytes:
                ev.id = str(event_id)
                return ev.encode()

            return await buf.append(encode_with_id)
        return ev.encode()

    def _modify_server_message(
        self, msg: dict[str, Any], backend: str
    ) -> dict[str, Any]:
        """Rewrites a server-initiated JSON-RPC message before relaying it
        to the client: request ids for ``roots/list`` /
        ``sampling/createMessage`` / ``elicitation/create`` become
        routable composites, as do server-issued ``_meta.progressToken``
        values (reference maybeServerToClientRequestModify,
        handlers.go:983-1070)."""
        if msg.get("method") not in S2C_REQUEST_METHODS:
            return msg
        if msg.get("id") is None:
            return msg
        msg = dict(msg, id=_encode_routed(S2C_ID_PREFIX, msg["id"], backend))
        params = msg.get("params")
        if isinstance(params, dict):
            meta = params.get("_meta")
            if isinstance(meta, dict) and "progressToken" in meta:
                token = _encode_routed(
                    PROGRESS_TOKEN_PREFIX, meta["progressToken"], backend
                )
                msg["params"] = dict(
                    params, _meta=dict(meta, progressToken=token)
                )
        return msg

    async def _client_to_server_response(
        self, payload: dict[str, Any], sessions: dict[str, str]
    ) -> web.Response:
        """Routes a client JSON-RPC response back to the backend that
        issued the server→client request (reference
        handleClientToServerResponse)."""
        rid = payload.get("id")
        if isinstance(rid, str) and rid.startswith(PING_ID_PREFIX):
            # reply to a gateway-initiated heartbeat ping — swallow
            # (reference doNotForwardResponseToBackends)
            return web.Response(status=202)
        decoded = _decode_routed(S2C_ID_PREFIX, rid)
        if decoded is None:
            return web.json_response(
                _rpc_error(None, -32600, f"invalid response ID {rid!r}"),
                status=400,
            )
        orig_id, backend_name = decoded
        backend = next(
            (b for b in self.cfg.backends if b.name == backend_name), None
        )
        if backend is None:
            return web.json_response(
                _rpc_error(None, -32602,
                           f"unknown backend {backend_name!r}"),
                status=404,
            )
        sid = sessions.get(backend_name, "")
        if not sid:
            return web.json_response(
                _rpc_error(None, -32602,
                           f"no session for backend {backend_name!r}"),
                status=400,
            )
        restored = dict(payload, id=orig_id)
        try:
            resp, _ = await self._call_backend(backend, restored, sid)
        except (aiohttp.ClientError, RuntimeError) as e:
            return web.json_response(
                _rpc_error(None, -32603, f"failed to forward: {e}"),
                status=502,
            )
        if resp is None:
            return web.Response(status=202)
        return web.json_response(resp)

    async def _route_progress(
        self, payload: dict[str, Any], sessions: dict[str, str]
    ) -> web.Response:
        """notifications/progress from the client carries a rewritten
        progressToken naming the backend that asked for progress
        (reference handleClientToServerNotificationsProgress)."""
        params = payload.get("params") or {}
        decoded = _decode_routed(
            PROGRESS_TOKEN_PREFIX, params.get("progressToken")
        )
        if decoded is None:
            return web.json_response(
                _rpc_error(
                    None, -32602,
                    f"invalid progressToken "
                    f"{params.get('progressToken')!r}",
                ),
                status=400,
            )
        token, backend_name = decoded
        backend = next(
            (b for b in self.cfg.backends if b.name == backend_name), None
        )
        sid = sessions.get(backend_name, "")
        if backend is None or not sid:
            return web.json_response(
                _rpc_error(None, -32602,
                           f"unknown backend {backend_name!r}"),
                status=400,
            )
        restored = dict(
            payload, params=dict(params, progressToken=token)
        )
        try:
            await self._call_backend(backend, restored, sid)
            if self.metrics is not None:
                # counted only once actually forwarded — rejected or
                # failed notifications must not corroborate traffic
                self.metrics.progress_notifications.inc()
        except (aiohttp.ClientError, RuntimeError) as e:
            logger.warning("progress forward to %s failed: %s",
                           backend_name, e)
        return web.Response(status=202)

    async def _aggregate_list(
        self, method: str, msg_id: Any, sessions: dict[str, str]
    ) -> dict[str, Any]:
        key = {
            "prompts/list": "prompts",
            "resources/list": "resources",
            "resources/templates/list": "resourceTemplates",
        }[method]

        async def one(b: MCPBackend):
            sid = sessions.get(b.name, "")
            if not sid:
                return []
            try:
                resp, _ = await self._call_backend(
                    b, {"jsonrpc": "2.0", "id": msg_id, "method": method}, sid
                )
            except (aiohttp.ClientError, RuntimeError):
                return []
            items = ((resp or {}).get("result") or {}).get(key) or []
            out = []
            for it in items:
                it = dict(it)
                if "name" in it:
                    it["name"] = f"{b.name}{TOOL_SEP}{it['name']}"
                out.append(it)
            return out

        lists = await asyncio.gather(*(one(b) for b in self.cfg.backends))
        return {
            "jsonrpc": "2.0",
            "id": msg_id,
            "result": {key: [x for sub in lists for x in sub]},
        }
