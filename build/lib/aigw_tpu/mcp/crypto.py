"""Session-ID encryption (reference internal/mcpproxy/crypto.go:
PBKDF2-derived AES-GCM with primary/fallback seeds for rotation).

The client-facing MCP session ID *is* the encrypted map of per-backend
session IDs — the gateway keeps no session table and any replica can
resume any session (reference session.go:51-66).
"""

from __future__ import annotations

import base64
import hashlib
import os

from cryptography.exceptions import InvalidTag
from cryptography.hazmat.primitives.ciphers.aead import AESGCM

_PBKDF2_ITERS = 100_000
_SALT = b"aigw-tpu-mcp-session"


class SessionCryptoError(Exception):
    pass


class SessionCrypto:
    """Encrypt/decrypt session payloads; fallback seed enables seamless
    key rotation (decrypt tries primary then fallback)."""

    def __init__(self, seed: str, fallback_seed: str = ""):
        self._keys = [self._derive(seed)]
        if fallback_seed:
            self._keys.append(self._derive(fallback_seed))

    @staticmethod
    def _derive(seed: str) -> AESGCM:
        key = hashlib.pbkdf2_hmac(
            "sha256", seed.encode(), _SALT, _PBKDF2_ITERS, dklen=32
        )
        return AESGCM(key)

    def encrypt(self, plaintext: bytes) -> str:
        nonce = os.urandom(12)
        ct = self._keys[0].encrypt(nonce, plaintext, None)
        return base64.urlsafe_b64encode(nonce + ct).decode().rstrip("=")

    def decrypt(self, token: str) -> bytes:
        try:
            raw = base64.urlsafe_b64decode(token + "=" * (-len(token) % 4))
        except Exception as e:
            raise SessionCryptoError(f"malformed session id: {e}") from None
        if len(raw) < 13:
            raise SessionCryptoError("session id too short")
        nonce, ct = raw[:12], raw[12:]
        for aead in self._keys:
            try:
                return aead.decrypt(nonce, ct, None)
            except InvalidTag:
                continue
        raise SessionCryptoError("session id failed authentication")
