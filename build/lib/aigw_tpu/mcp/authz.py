"""MCP authorization: JWT validation + tool-level claim rules.

Reference: internal/mcpproxy/authorization.go — OAuth2 protected-resource
metadata, JWT validation per ``MCPRouteAuthorizationRule``, tool-level
claims matching (api/v1alpha1/mcp_route.go JWTSource/JWKS rules).

Self-contained JWS verification (no PyJWT in the image): HS256 via hmac,
RS256 via the cryptography package. Checks exp/nbf/iss/aud, then matches
tool-glob + required-claim rules.
"""

from __future__ import annotations

import base64
import fnmatch
import hashlib
import hmac
import json
import time
from dataclasses import dataclass, field
from typing import Any


class AuthzError(Exception):
    """Token missing/invalid (→ 401) or not permitted (→ 403)."""

    def __init__(self, message: str, status: int = 401):
        super().__init__(message)
        self.status = status


def _b64url(data: str) -> bytes:
    return base64.urlsafe_b64decode(data + "=" * (-len(data) % 4))


@dataclass(frozen=True)
class AuthzRule:
    """Allow tools matching ``tools`` globs to callers whose JWT carries
    all ``claims`` (values compared as strings; list claims match any)."""

    tools: tuple[str, ...] = ("*",)
    claims: tuple[tuple[str, str], ...] = ()

    def permits(self, tool: str, token_claims: dict[str, Any]) -> bool:
        if not any(fnmatch.fnmatch(tool, p) for p in self.tools):
            return False
        for name, want in self.claims:
            have = token_claims.get(name)
            if isinstance(have, list):
                if want not in [str(x) for x in have]:
                    return False
            elif str(have) != want:
                return False
        return True


@dataclass(frozen=True)
class MCPAuthzConfig:
    hs256_secret: str = ""
    rs256_public_key_pem: str = ""
    issuer: str = ""
    audience: str = ""
    rules: tuple[AuthzRule, ...] = ()
    # served at /.well-known/oauth-protected-resource (RFC 9728)
    resource: str = ""
    authorization_servers: tuple[str, ...] = ()

    @staticmethod
    def parse(value: dict[str, Any] | None) -> "MCPAuthzConfig | None":
        if not value:
            return None
        jwt = value.get("jwt") or {}
        rules = tuple(
            AuthzRule(
                tools=tuple(r.get("tools", ("*",))),
                claims=tuple(
                    (str(k), str(v))
                    for k, v in (r.get("claims") or {}).items()
                ),
            )
            for r in value.get("rules", ())
        ) or (AuthzRule(),)
        secret = jwt.get("hs256_secret", "")
        if secret.startswith("file:"):
            with open(secret[5:], "r", encoding="utf-8") as f:
                secret = f.read().strip()
        pem = jwt.get("rs256_public_key_pem", "")
        if pem.startswith("file:"):
            with open(pem[5:], "r", encoding="utf-8") as f:
                pem = f.read()
        if not secret and not pem:
            raise ValueError(
                "mcp.authorization.jwt needs hs256_secret or "
                "rs256_public_key_pem"
            )
        return MCPAuthzConfig(
            hs256_secret=secret,
            rs256_public_key_pem=pem,
            issuer=jwt.get("issuer", ""),
            audience=jwt.get("audience", ""),
            rules=rules,
            resource=value.get("resource", ""),
            authorization_servers=tuple(
                value.get("authorization_servers", ())
            ),
        )


class JWTValidator:
    def __init__(self, cfg: MCPAuthzConfig):
        self.cfg = cfg
        self._rsa_key = None
        if cfg.rs256_public_key_pem:
            from cryptography.hazmat.primitives.serialization import (
                load_pem_public_key,
            )

            self._rsa_key = load_pem_public_key(
                cfg.rs256_public_key_pem.encode()
            )

    def validate(self, token: str) -> dict[str, Any]:
        """Verify signature + standard claims; returns the claim set."""
        try:
            header_b64, payload_b64, sig_b64 = token.split(".")
            header = json.loads(_b64url(header_b64))
            payload = json.loads(_b64url(payload_b64))
            sig = _b64url(sig_b64)
        except (ValueError, json.JSONDecodeError) as e:
            raise AuthzError(f"malformed JWT: {e}") from None
        signing_input = f"{header_b64}.{payload_b64}".encode()

        alg = header.get("alg")
        if alg == "HS256" and self.cfg.hs256_secret:
            want = hmac.new(self.cfg.hs256_secret.encode(), signing_input,
                            hashlib.sha256).digest()
            if not hmac.compare_digest(want, sig):
                raise AuthzError("JWT signature invalid")
        elif alg == "RS256" and self._rsa_key is not None:
            from cryptography.exceptions import InvalidSignature
            from cryptography.hazmat.primitives import hashes
            from cryptography.hazmat.primitives.asymmetric import padding

            try:
                self._rsa_key.verify(sig, signing_input, padding.PKCS1v15(),
                                     hashes.SHA256())
            except InvalidSignature:
                raise AuthzError("JWT signature invalid") from None
        else:
            raise AuthzError(f"unsupported/unconfigured JWT alg {alg!r}")

        now = time.time()
        if "exp" in payload and now >= float(payload["exp"]):
            raise AuthzError("JWT expired")
        if "nbf" in payload and now < float(payload["nbf"]):
            raise AuthzError("JWT not yet valid")
        if self.cfg.issuer and payload.get("iss") != self.cfg.issuer:
            raise AuthzError("JWT issuer mismatch")
        if self.cfg.audience:
            aud = payload.get("aud")
            auds = aud if isinstance(aud, list) else [aud]
            if self.cfg.audience not in auds:
                raise AuthzError("JWT audience mismatch")
        return payload

    def authorize_tool(self, tool: str, claims: dict[str, Any]) -> None:
        if not any(r.permits(tool, claims) for r in self.cfg.rules):
            raise AuthzError(
                f"tool {tool!r} not permitted for this principal", status=403
            )


def sign_hs256(claims: dict[str, Any], secret: str) -> str:
    """Test helper: mint an HS256 JWT."""

    def enc(obj: Any) -> str:
        return base64.urlsafe_b64encode(
            json.dumps(obj).encode()
        ).rstrip(b"=").decode()

    head = enc({"alg": "HS256", "typ": "JWT"})
    body = enc(claims)
    sig = hmac.new(secret.encode(), f"{head}.{body}".encode(),
                   hashlib.sha256).digest()
    return f"{head}.{body}." + base64.urlsafe_b64encode(sig).rstrip(
        b"=").decode()
