"""Sharded, checksummed config bundles.

The reference splits the marshalled config into ≤1MB-safe parts with an
``index.yaml`` carrying SHA-256 checksums so a half-written update is never
loaded (internal/controller/filter_config_bundle.go:31-125,
internal/filterapi/config_bundle.go:19-66). We reproduce the same scheme on
a directory: ``index.json`` + ``part-N.json``.
"""

from __future__ import annotations

import hashlib
import json
import os
import uuid as uuidlib
from typing import Any

from aigw_tpu.config.model import Config, ConfigError

DEFAULT_PART_SIZE = 1 << 20  # 1 MiB, the reference's Secret-size bound


def write_bundle(cfg: Config, directory: str, part_size: int = DEFAULT_PART_SIZE) -> str:
    """Write cfg as a sharded bundle; returns the bundle UUID.

    Parts are written before the index so a concurrent reader either sees a
    complete consistent bundle or fails the checksum gate and keeps its
    current config (the reference's atomicity strategy,
    filter_config_bundle.go:46).
    """
    os.makedirs(directory, exist_ok=True)
    bundle_uuid = cfg.uuid or str(uuidlib.uuid4())
    data = dict(cfg.to_dict())
    data["uuid"] = bundle_uuid
    blob = json.dumps(data, sort_keys=True).encode()
    parts = [blob[i : i + part_size] for i in range(0, len(blob), part_size)] or [b""]
    index: dict[str, Any] = {
        "uuid": bundle_uuid,
        "version": cfg.version,
        "parts": [],
    }
    for i, part in enumerate(parts):
        name = f"part-{i}.json"
        with open(os.path.join(directory, name), "wb") as f:
            f.write(part)
        index["parts"].append(
            {"name": name, "sha256": hashlib.sha256(part).hexdigest()}
        )
    tmp = os.path.join(directory, ".index.json.tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(index, f)
    os.replace(tmp, os.path.join(directory, "index.json"))
    return bundle_uuid


def read_bundle(directory: str) -> Config:
    """Read and checksum-verify a bundle directory → Config."""
    index_path = os.path.join(directory, "index.json")
    with open(index_path, "r", encoding="utf-8") as f:
        index = json.load(f)
    blob = b""
    for part in index["parts"]:
        with open(os.path.join(directory, part["name"]), "rb") as f:
            data = f.read()
        digest = hashlib.sha256(data).hexdigest()
        if digest != part["sha256"]:
            raise ConfigError(
                f"bundle part {part['name']} checksum mismatch "
                f"(expected {part['sha256'][:12]}…, got {digest[:12]}…)"
            )
        blob += data
    cfg = Config.parse(json.loads(blob.decode()))
    return cfg
