"""RuntimeConfig — a Config with everything pre-built for the hot path.

Equivalent of the reference's ``filterapi.RuntimeConfig``
(filterapi/runtime.go:29-73): auth handlers constructed, cost expressions
compiled, routes indexed — so per-request processing never touches parsing
or compilation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from aigw_tpu.config.model import Backend, Config, Route


@dataclass
class RuntimeBackend:
    """A Backend plus its pre-built auth handler."""

    backend: Backend
    auth_handler: Any  # aigw_tpu.gateway.auth.AuthHandler


@dataclass
class RuntimeConfig:
    config: Config
    backends: dict[str, RuntimeBackend] = field(default_factory=dict)
    cost_calculator: Any = None  # aigw_tpu.gateway.costs.CostCalculator
    # per-route calculators (global costs + route-level overrides)
    route_cost_calculators: dict[str, Any] = field(default_factory=dict)
    rate_limiter: Any = None  # aigw_tpu.gateway.ratelimit.RateLimiter

    @staticmethod
    def build(config: Config,
              previous: "RuntimeConfig | None" = None) -> "RuntimeConfig":
        # Local imports keep aigw_tpu.config importable without the gateway
        # package (mirrors the filterapi/extproc layering of the reference).
        from aigw_tpu.gateway.auth import new_handler
        from aigw_tpu.gateway.costs import CostCalculator
        from aigw_tpu.gateway.ratelimit import RateLimiter
        from aigw_tpu.config.model import _thaw

        config.validate()
        rc = RuntimeConfig(config=config)
        for b in config.backends:
            rc.backends[b.name] = RuntimeBackend(
                backend=b, auth_handler=new_handler(b.auth)
            )
        rc.cost_calculator = CostCalculator.from_config(config)
        global_costs = {c.metadata_key: c for c in config.llm_request_costs}
        for route in config.routes:
            if route.llm_request_costs:
                merged = dict(global_costs)
                merged.update(
                    {c.metadata_key: c for c in route.llm_request_costs}
                )
                rc.route_cost_calculators[route.name] = CostCalculator(
                    tuple(merged.values())
                )
        rc.rate_limiter = RateLimiter.from_config_value(
            [_thaw(q) for q in config.quotas]
        ).adopt(previous.rate_limiter if previous else None)
        return rc

    def cost_calculator_for(self, route_name: str):
        return self.route_cost_calculators.get(route_name,
                                               self.cost_calculator)

    def routes_for_host(self, host: str) -> list[Route]:
        host = host.split(":")[0].lower()
        out = []
        for r in self.config.routes:
            if not r.hostnames or host in r.hostnames:
                out.append(r)
        return out
