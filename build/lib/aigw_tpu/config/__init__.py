"""Gateway configuration model, compiler and hot-reload watcher.

Equivalent of the reference's decoupled data-plane config
(``internal/filterapi/filterconfig.go:25`` — "must not be tied to k8s")
plus the controller's config generation (``internal/controller/gateway.go:348``).
"""

from aigw_tpu.config.model import (
    APISchema,
    APISchemaName,
    AuthConfig,
    Backend,
    BodyMutation,
    Config,
    ConfigError,
    HeaderMutation,
    LLMRequestCost,
    LLMRequestCostType,
    Model,
    Route,
    RouteRule,
    RuleBackendRef,
    MODEL_NAME_HEADER,
)
from aigw_tpu.config.runtime import RuntimeConfig
from aigw_tpu.config.watcher import ConfigWatcher
from aigw_tpu.config.bundle import write_bundle, read_bundle

__all__ = [
    "APISchema",
    "APISchemaName",
    "AuthConfig",
    "Backend",
    "BodyMutation",
    "Config",
    "ConfigError",
    "ConfigWatcher",
    "HeaderMutation",
    "LLMRequestCost",
    "LLMRequestCostType",
    "MODEL_NAME_HEADER",
    "Model",
    "Route",
    "RouteRule",
    "RuleBackendRef",
    "RuntimeConfig",
    "read_bundle",
    "write_bundle",
]
