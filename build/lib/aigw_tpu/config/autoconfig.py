"""Zero-config from environment variables.

Equivalent of the reference's internal/autoconfig (config.go:24-110):
``aigw-tpu run`` with no config file builds a working gateway from
whatever provider credentials the environment carries:

- ``OPENAI_API_KEY``       → OpenAI backend (``OPENAI_BASE_URL`` optional)
- ``ANTHROPIC_API_KEY``    → Anthropic backend
- ``AZURE_OPENAI_API_KEY`` + ``AZURE_OPENAI_ENDPOINT`` → Azure backend
- ``TPUSERVE_URL``         → in-tree TPU serving backend
- ``AIGW_MODELS``          → comma-separated model names to route
                              (default: route any model to the first
                              backend via a catch-all rule)
"""

from __future__ import annotations

import os
from typing import Any

from aigw_tpu.config.model import Config, ConfigError


def autoconfig_from_env(env: dict[str, str] | None = None) -> Config:
    env = dict(os.environ) if env is None else env
    backends: list[dict[str, Any]] = []

    if env.get("TPUSERVE_URL"):
        backends.append(
            {"name": "tpuserve", "schema": "TPUServe",
             "url": env["TPUSERVE_URL"]}
        )
    if env.get("OPENAI_API_KEY"):
        backends.append(
            {
                "name": "openai",
                "schema": "OpenAI",
                "url": env.get("OPENAI_BASE_URL", "https://api.openai.com"),
                "auth": {"kind": "APIKey", "api_key": env["OPENAI_API_KEY"]},
            }
        )
    if env.get("ANTHROPIC_API_KEY"):
        backends.append(
            {
                "name": "anthropic",
                "schema": "Anthropic",
                "url": env.get("ANTHROPIC_BASE_URL",
                               "https://api.anthropic.com"),
                "auth": {"kind": "AnthropicAPIKey",
                         "api_key": env["ANTHROPIC_API_KEY"]},
            }
        )
    if env.get("AZURE_OPENAI_API_KEY") and env.get("AZURE_OPENAI_ENDPOINT"):
        backends.append(
            {
                "name": "azure",
                "schema": {"name": "AzureOpenAI",
                           "version": env.get("AZURE_OPENAI_API_VERSION",
                                              "")},
                "url": env["AZURE_OPENAI_ENDPOINT"],
                "auth": {"kind": "AzureAPIKey",
                         "azure_api_key": env["AZURE_OPENAI_API_KEY"]},
            }
        )
    if not backends:
        raise ConfigError(
            "autoconfig found no credentials: set OPENAI_API_KEY, "
            "ANTHROPIC_API_KEY, AZURE_OPENAI_API_KEY+AZURE_OPENAI_ENDPOINT, "
            "or TPUSERVE_URL (or pass a config file)"
        )

    models = [m.strip() for m in env.get("AIGW_MODELS", "").split(",")
              if m.strip()]
    names = [b["name"] for b in backends]
    rules: list[dict[str, Any]] = []
    if models:
        rules.append({"models": models, "backends": [names[0]]})
    # model-prefix routing so every configured provider is reachable:
    # claude-* → Anthropic, gpt-*/o* → OpenAI-schema backends
    if "anthropic" in names:
        rules.append({"model_prefixes": ["claude"],
                      "backends": ["anthropic"]})
    openai_like = [n for n in ("openai", "azure") if n in names]
    if openai_like:
        rules.append({"model_prefixes": ["gpt", "o1", "o3", "o4",
                                         "text-embedding", "chatgpt"],
                      "backends": openai_like})
    # catch-all: every backend forms a priority fallback chain
    rules.append({
        "backends": [
            {"backend": n, "priority": i} for i, n in enumerate(names)
        ]
    })

    return Config.parse(
        {
            "version": "v1",
            "backends": backends,
            "routes": [{"name": "autoconfig", "rules": rules}],
            "models": models,
            "llm_request_costs": [
                {"metadata_key": "total_tokens", "type": "TotalToken"}
            ],
        }
    )
