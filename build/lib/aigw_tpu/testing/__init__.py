"""Test infrastructure shipped with the package (fake providers,
cassette record/replay) — the reference ships the equivalent under
``tests/internal/testopenai`` as an importable package."""

from aigw_tpu.testing.cassettes import (  # noqa: F401
    Cassette,
    CassetteServer,
    Interaction,
    load_cassette,
)
