"""Provider-parity cassette record/replay harness.

The reference's strongest translator-correctness tool is a fake OpenAI
server that replays **real recorded provider interactions** keyed by an
``X-Cassette-Name`` header (``tests/internal/testopenai/README.md:1-60``,
go-vcr v2 YAML cassettes). This module is the tpu-native equivalent:

- ``load_cassette`` reads both the public go-vcr v2 YAML format (so the
  reference's own recordings can be replayed in place, without copying
  them into this repo) and a native JSON format for new recordings.
- ``CassetteServer`` is an aiohttp fake upstream that matches incoming
  requests to a cassette by the ``x-cassette-name`` header (fallback:
  request path), replays the recorded status/headers/body, and chunks
  ``text/event-stream`` bodies per event so streaming translators see
  realistic chunk boundaries.
- ``CassetteServer(record_base=...)`` proxies unmatched requests to a
  live provider and writes a JSON cassette — the recording workflow for
  refreshing fixtures when credentials and egress exist.

Wire fixtures stay the provider's own bytes: tests assert translators
against what OpenAI/Azure actually sent, not hand-written expectations.
"""

from __future__ import annotations

import json
import logging
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from aiohttp import web

logger = logging.getLogger(__name__)

CASSETTE_HEADER = "x-cassette-name"


@dataclass
class Interaction:
    method: str
    url: str
    path: str
    request_body: str
    request_headers: dict[str, str]
    status: int
    response_body: str
    response_headers: dict[str, str]

    @property
    def is_sse(self) -> bool:
        ctype = self.response_headers.get("content-type", "")
        return "text/event-stream" in ctype


@dataclass
class Cassette:
    name: str
    interactions: list[Interaction] = field(default_factory=list)


def _flatten_headers(h: dict[str, Any] | None) -> dict[str, str]:
    out: dict[str, str] = {}
    for k, v in (h or {}).items():
        if isinstance(v, list):
            if v:
                out[str(k).lower()] = str(v[0])
        else:
            out[str(k).lower()] = str(v)
    return out


def _path_of(url: str) -> str:
    m = re.match(r"https?://[^/]+(/.*)?$", url or "")
    return (m.group(1) or "/") if m else (url or "/")


def load_cassette(path: str | Path) -> Cassette:
    """Reads a go-vcr v2 YAML cassette or a native JSON cassette."""
    p = Path(path)
    raw = p.read_text()
    if p.suffix in (".yaml", ".yml"):
        import yaml

        doc = yaml.safe_load(raw)
        interactions = []
        for it in doc.get("interactions") or []:
            req = it.get("request") or {}
            resp = it.get("response") or {}
            interactions.append(Interaction(
                method=req.get("method", "POST"),
                url=req.get("url", ""),
                path=_path_of(req.get("url", "")),
                request_body=req.get("body") or "",
                request_headers=_flatten_headers(req.get("headers")),
                status=int(resp.get("code", 200)),
                response_body=resp.get("body") or "",
                response_headers=_flatten_headers(resp.get("headers")),
            ))
        return Cassette(name=p.stem, interactions=interactions)
    doc = json.loads(raw)
    return Cassette(
        name=doc.get("name", p.stem),
        interactions=[Interaction(**it) for it in doc["interactions"]],
    )


def dump_cassette(cassette: Cassette, path: str | Path) -> None:
    """Writes the native JSON format."""
    Path(path).write_text(json.dumps({
        "name": cassette.name,
        "interactions": [vars(it) for it in cassette.interactions],
    }, indent=2))


# headers that must not be replayed verbatim (transfer framing is ours;
# auth material must never leak out of fixtures)
_SKIP_REPLAY_HEADERS = {
    "content-length", "transfer-encoding", "content-encoding",
    "connection", "set-cookie", "authorization",
}


class CassetteServer:
    """Fake upstream replaying recorded interactions.

    Matching: the ``x-cassette-name`` header selects the cassette (like
    the reference); within it, the first interaction whose method+path
    match is replayed. Without the header, the first loaded cassette
    with a matching method+path wins (convenient for single-cassette
    gateway tests, where the gateway doesn't forward custom headers).
    """

    def __init__(self, record_base: str = "",
                 record_dir: str | Path | None = None):
        self._cassettes: dict[str, Cassette] = {}
        self._order: list[str] = []
        self._consumed: set[int] = set()
        self._record_base = record_base.rstrip("/")
        self._record_dir = Path(record_dir) if record_dir else None
        self._app = web.Application()
        self._app.router.add_route("*", "/{tail:.*}", self._dispatch)
        self._runner: web.AppRunner | None = None
        self.url = ""
        self.requests: list[tuple[str, str, bytes]] = []  # observability

    def load(self, *paths: str | Path) -> "CassetteServer":
        for p in paths:
            c = load_cassette(p)
            self._cassettes[c.name] = c
            self._order.append(c.name)
        return self

    def load_dir(self, directory: str | Path,
                 pattern: str = "*.yaml") -> "CassetteServer":
        for p in sorted(Path(directory).glob(pattern)):
            if p.name == "README.md":
                continue
            self.load(p)
        return self

    def _match(self, name: str, method: str,
               path: str) -> Interaction | None:
        """First *unconsumed* method+path match — go-vcr semantics:
        multi-interaction cassettes (e.g. a recorded multi-turn
        conversation hitting the same endpoint twice) replay in order.
        When every match is consumed, the last one replays again so
        repeated identical requests stay serviceable; ``reset()``
        rearms everything."""
        names = [name] if name else self._order
        last: Interaction | None = None
        for n in names:
            c = self._cassettes.get(n)
            if c is None:
                continue
            for it in c.interactions:
                if it.method.upper() == method.upper() and it.path == path:
                    if id(it) not in self._consumed:
                        self._consumed.add(id(it))
                        return it
                    last = it
        return last

    def reset(self) -> None:
        """Rearm consumed interactions (fresh replay sequence)."""
        self._consumed.clear()

    async def _dispatch(self, request: web.Request) -> web.StreamResponse:
        body = await request.read()
        self.requests.append((request.method, request.path, body))
        name = request.headers.get(CASSETTE_HEADER, "")
        it = self._match(name, request.method, request.path)
        if it is None and self._record_base:
            return await self._record(request, body, name)
        if it is None:
            return web.json_response(
                {"error": {"message":
                           f"no cassette interaction for "
                           f"{request.method} {request.path} "
                           f"(cassette {name!r})"}},
                status=404,
            )
        headers = {k: v for k, v in it.response_headers.items()
                   if k not in _SKIP_REPLAY_HEADERS}
        if it.is_sse:
            resp = web.StreamResponse(status=it.status, headers=headers)
            await resp.prepare(request)
            # chunk per SSE event: translators must handle realistic
            # boundaries, not one giant buffer
            for event in it.response_body.split("\n\n"):
                if not event.strip():
                    continue
                await resp.write((event + "\n\n").encode())
            await resp.write_eof()
            return resp
        return web.Response(status=it.status, body=it.response_body,
                            headers=headers)

    async def _record(self, request: web.Request, body: bytes,
                      name: str) -> web.Response:
        """Proxy to the live provider and persist the interaction
        (requires egress + credentials; replay-only environments never
        reach this)."""
        import aiohttp

        url = self._record_base + request.path
        headers = {k: v for k, v in request.headers.items()
                   if k.lower() not in ("host", CASSETTE_HEADER)}
        async with aiohttp.ClientSession() as s:
            async with s.request(request.method, url, data=body,
                                 headers=headers) as upstream:
                resp_body = await upstream.read()
                interaction = Interaction(
                    method=request.method,
                    url=url,
                    path=request.path,
                    request_body=body.decode("utf-8", "replace"),
                    request_headers={
                        k.lower(): v for k, v in request.headers.items()
                        if k.lower() not in ("authorization",)
                    },
                    status=upstream.status,
                    response_body=resp_body.decode("utf-8", "replace"),
                    response_headers={
                        k.lower(): v
                        for k, v in upstream.headers.items()
                        if k.lower() not in _SKIP_REPLAY_HEADERS
                    },
                )
        cname = name or "recorded"
        c = self._cassettes.setdefault(cname, Cassette(name=cname))
        if cname not in self._order:
            self._order.append(cname)
        c.interactions.append(interaction)
        if self._record_dir is not None:
            self._record_dir.mkdir(parents=True, exist_ok=True)
            dump_cassette(c, self._record_dir / f"{cname}.json")
        return web.Response(status=interaction.status,
                            body=resp_body)

    async def start(self) -> "CassetteServer":
        self._runner = web.AppRunner(self._app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        self.url = f"http://127.0.0.1:{port}"
        return self

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()
