"""Anthropic Messages API schema helpers
(reference internal/apischema/anthropic/anthropic.go).
"""

from __future__ import annotations

import time
import uuid
from typing import Any

from aigw_tpu.gateway.costs import TokenUsage
from aigw_tpu.schemas.openai import SchemaError

#: Anthropic stop_reason → OpenAI finish_reason
STOP_REASON_TO_OPENAI = {
    "end_turn": "stop",
    "stop_sequence": "stop",
    "max_tokens": "length",
    "tool_use": "tool_calls",
    "refusal": "content_filter",
}
#: OpenAI finish_reason → Anthropic stop_reason
FINISH_REASON_TO_ANTHROPIC = {
    "stop": "end_turn",
    "length": "max_tokens",
    "tool_calls": "tool_use",
    "content_filter": "refusal",
    "function_call": "tool_use",
}

DEFAULT_MAX_TOKENS = 4096  # Anthropic requires max_tokens; OpenAI does not.


def validate_messages_request(body: dict[str, Any]) -> None:
    if not isinstance(body.get("model"), str) or not body["model"]:
        raise SchemaError("missing required field: model")
    if not isinstance(body.get("messages"), list) or not body["messages"]:
        raise SchemaError("messages must be a non-empty array")
    if not isinstance(body.get("max_tokens"), int):
        raise SchemaError("missing required field: max_tokens")
    for i, m in enumerate(body["messages"]):
        # "system" is permitted in the array (mid-conversation system
        # prompts; some clients send them as messages rather than the
        # top-level parameter — reference
        # promoteAnthropicSystemMessagesToParam)
        if not isinstance(m, dict) or m.get("role") not in (
                "user", "assistant", "system"):
            raise SchemaError(
                f"messages[{i}] must have role user|assistant|system")


def promote_system_messages(body: dict[str, Any]) -> dict[str, Any]:
    """Return a new request body with any role:"system" messages removed
    from the array and their text folded into the top-level ``system``
    parameter (reference promoteAnthropicSystemMessagesToParam — the
    Anthropic upstream itself rejects role:system in messages, so
    passthrough backends need the promotion too). No-op (same dict) when
    no system messages are present."""
    messages = body.get("messages")
    if not isinstance(messages, list) or not any(
        isinstance(m, dict) and m.get("role") == "system" for m in messages
    ):
        return body
    promoted: list[str] = []
    kept: list[Any] = []
    for m in messages:
        if isinstance(m, dict) and m.get("role") == "system":
            content = m.get("content")
            text = (content if isinstance(content, str)
                    else text_of_blocks(content_blocks(content)))
            if text:
                promoted.append(text)
        else:
            kept.append(m)
    out = dict(body, messages=kept)
    sys_param = body.get("system")
    if isinstance(sys_param, list):
        # block-form system param: preserve the original blocks verbatim
        # (cache_control etc. must survive) and append promoted text as
        # new blocks
        out["system"] = list(sys_param) + [
            {"type": "text", "text": t} for t in promoted
        ]
    else:
        parts = ([sys_param] if isinstance(sys_param, str) and sys_param
                 else []) + promoted
        system = "\n".join(parts)
        if system:
            out["system"] = system
    return out


def content_blocks(content: Any) -> list[dict[str, Any]]:
    """Normalize the string-or-blocks content union to a block list."""
    if isinstance(content, str):
        return [{"type": "text", "text": content}]
    if isinstance(content, list):
        return [b for b in content if isinstance(b, dict)]
    raise SchemaError(f"invalid content type {type(content).__name__}")


def text_of_blocks(blocks: list[dict[str, Any]]) -> str:
    return "".join(b.get("text", "") for b in blocks if b.get("type") == "text")


def extract_usage(body: dict[str, Any]) -> TokenUsage:
    u = body.get("usage")
    if not isinstance(u, dict):
        return TokenUsage()
    inp = int(u.get("input_tokens", 0) or 0)
    out = int(u.get("output_tokens", 0) or 0)
    cached = int(u.get("cache_read_input_tokens", 0) or 0)
    cache_creation = int(u.get("cache_creation_input_tokens", 0) or 0)
    return TokenUsage(
        input_tokens=inp,
        output_tokens=out,
        total_tokens=(inp + out) if (inp or out) else 0,
        cached_input_tokens=cached,
        cache_creation_input_tokens=cache_creation,
    )


def messages_response(
    *,
    model: str,
    content: list[dict[str, Any]],
    stop_reason: str = "end_turn",
    usage: TokenUsage | None = None,
    response_id: str = "",
) -> dict[str, Any]:
    usage = usage or TokenUsage()
    return {
        "id": response_id or f"msg_{uuid.uuid4().hex[:24]}",
        "type": "message",
        "role": "assistant",
        "model": model,
        "content": content,
        "stop_reason": stop_reason,
        "stop_sequence": None,
        "usage": {
            "input_tokens": usage.input_tokens,
            "output_tokens": usage.output_tokens,
        },
    }


def error_body(message: str, type_: str = "invalid_request_error") -> bytes:
    import json

    return json.dumps(
        {"type": "error", "error": {"type": type_, "message": message}}
    ).encode()
