"""Provider API schema helpers (reference internal/apischema).

Bodies are handled as parsed JSON (dicts) with typed accessor/validator
helpers per schema, rather than exhaustive struct mirrors: translation
composes better over dicts, and unknown provider fields pass through
unharmed (the reference preserves unknown fields through sjson edits for
the same reason, translator.go:140-153).
"""
