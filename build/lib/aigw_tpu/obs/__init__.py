"""Observability: OTel GenAI metrics + tracing (reference internal/metrics,
internal/tracing)."""
