"""Per-backend circuit breaker (Envoy outlier-detection parity).

The reference data plane gets passive health checking from Envoy (outlier
ejection on consecutive 5xx, reference cluster config); natively: after
``threshold`` consecutive failures a backend's circuit opens for
``cooldown`` seconds and the selector skips it, except when every
candidate is open (fail-static: better to try a suspect backend than to
reject outright). Any success closes the circuit.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class _State:
    consecutive_failures: int = 0
    open_until: float = 0.0


class CircuitBreaker:
    def __init__(self, threshold: int = 5, cooldown: float = 15.0):
        self.threshold = threshold
        self.cooldown = cooldown
        self._states: dict[str, _State] = {}

    def _state(self, backend: str) -> _State:
        st = self._states.get(backend)
        if st is None:
            st = _State()
            self._states[backend] = st
        return st

    def record_success(self, backend: str) -> None:
        st = self._state(backend)
        st.consecutive_failures = 0
        st.open_until = 0.0

    def record_failure(self, backend: str, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        st = self._state(backend)
        st.consecutive_failures += 1
        if st.consecutive_failures >= self.threshold:
            st.open_until = now + self.cooldown

    def is_open(self, backend: str, now: float | None = None) -> bool:
        now = time.monotonic() if now is None else now
        st = self._states.get(backend)
        return st is not None and now < st.open_until

    def snapshot(self) -> dict[str, dict]:
        now = time.monotonic()
        return {
            name: {
                "consecutive_failures": st.consecutive_failures,
                "open_for_s": max(0.0, round(st.open_until - now, 1)),
            }
            for name, st in self._states.items()
            if st.consecutive_failures or st.open_until > now
        }
