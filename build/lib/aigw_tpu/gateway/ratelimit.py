"""Token-budget quotas and rate limiting.

Equivalent of the reference's QuotaPolicy CRD + Envoy ratelimit service leg
(api/v1alpha1/quota_policy.go:26-165, internal/ratelimit/translator —
descriptor trees keyed backend/model/client selectors) collapsed into one
in-process engine, keeping the reference's semantics:

- **Enforcement at request time, consumption at end-of-stream**: token
  costs are only known after the response completes, so a request is
  admitted if its descriptor buckets currently have budget, and the actual
  cost is drawn down afterwards (Envoy's ``apply_on_stream_done``,
  filterconfig.go:84-87). A burst can therefore overshoot one window by
  in-flight requests — the same behavior as the reference.
- **Descriptors**: (rule, model, backend, client-key) tuples; the client
  key comes from a configurable request header.
- **Fixed windows** aligned to the unit boundary, like the Envoy ratelimit
  service's per-unit counters.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

from aigw_tpu.config.model import ConfigError


@dataclass(frozen=True)
class QuotaRule:
    """One quota: budget of a cost metric per time window, optionally
    scoped to model/backend and keyed by a client header."""

    name: str
    metadata_key: str  # which LLMRequestCost metric to draw down
    limit: int
    window_seconds: float = 60.0
    model: str = ""  # "" = any
    backend: str = ""  # "" = any
    client_key_header: str = ""  # "" = one global bucket

    @staticmethod
    def parse(value: dict[str, Any]) -> "QuotaRule":
        try:
            rule = QuotaRule(
                name=value["name"],
                metadata_key=value["metadata_key"],
                limit=int(value["limit"]),
                window_seconds=float(value.get("window_seconds", 60.0)),
                model=value.get("model", ""),
                backend=value.get("backend", ""),
                client_key_header=str(
                    value.get("client_key_header", "")
                ).lower(),
            )
        except KeyError as e:
            raise ConfigError(f"quota rule missing field {e}") from None
        if rule.limit <= 0 or rule.window_seconds <= 0:
            raise ConfigError(f"quota {rule.name}: limit/window must be > 0")
        return rule


@dataclass
class _Window:
    start: float
    used: int


class RateLimiter:
    """In-process descriptor-keyed fixed-window limiter."""

    _SWEEP_EVERY = 1024  # bucket insertions between stale-window sweeps

    def __init__(self, rules: list[QuotaRule]):
        self.rules = rules
        self._windows: dict[tuple[str, str], _Window] = {}
        self._inserts = 0
        self._window_by_rule = {r.name: r.window_seconds for r in rules}

    def adopt(self, previous: "RateLimiter | None") -> "RateLimiter":
        """Carry in-flight window counters across a config hot reload so
        a reload never refills exhausted budgets (rules are matched by
        name+shape; changed rules start fresh)."""
        if previous is None:
            return self
        prev_rules = {r.name: r for r in previous.rules}
        keep = {
            r.name for r in self.rules if prev_rules.get(r.name) == r
        }
        for key, window in previous._windows.items():
            if key[0] in keep:
                self._windows[key] = window
        return self

    @staticmethod
    def from_config_value(value: Any) -> "RateLimiter":
        rules = [QuotaRule.parse(v) for v in (value or ())]
        return RateLimiter(rules)

    def _matching(self, model: str, backend: str) -> list[QuotaRule]:
        return [
            r
            for r in self.rules
            if (not r.model or r.model == model)
            and (not r.backend or r.backend == backend)
        ]

    def _bucket(self, rule: QuotaRule, client_key: str,
                now: float) -> _Window:
        key = (rule.name, client_key)
        w = self._windows.get(key)
        window_start = now - (now % rule.window_seconds)
        if w is None or w.start != window_start:
            w = _Window(start=window_start, used=0)
            self._windows[key] = w
            self._inserts += 1
            if self._inserts % self._SWEEP_EVERY == 0:
                self._sweep(now)
        return w

    def _sweep(self, now: float) -> None:
        """Evict expired windows so client-controlled keys can't grow
        memory without bound."""
        dead = [
            k
            for k, w in self._windows.items()
            if now - w.start > 2 * self._window_by_rule.get(k[0], 3600.0)
        ]
        for k in dead:
            del self._windows[k]

    def check(
        self,
        model: str,
        backend: str,
        headers: dict[str, str],
        now: float | None = None,
    ) -> tuple[bool, "QuotaRule | None"]:
        """(True, None) if the request may proceed; otherwise
        (False, the violated rule)."""
        now = time.time() if now is None else now
        for rule in self._matching(model, backend):
            client_key = headers.get(rule.client_key_header, "") \
                if rule.client_key_header else ""
            w = self._bucket(rule, client_key, now)
            if w.used >= rule.limit:
                return False, rule
        return True, None

    def consume(
        self,
        costs: dict[str, int],
        model: str,
        backend: str,
        headers: dict[str, str],
        now: float | None = None,
    ) -> None:
        """Draw down matched buckets at end-of-stream."""
        now = time.time() if now is None else now
        for rule in self._matching(model, backend):
            cost = costs.get(rule.metadata_key)
            if not cost:
                continue
            client_key = headers.get(rule.client_key_header, "") \
                if rule.client_key_header else ""
            self._bucket(rule, client_key, now).used += cost

    def remaining(
        self, rule_name: str, client_key: str = "", now: float | None = None
    ) -> int | None:
        for rule in self.rules:
            if rule.name == rule_name:
                now = time.time() if now is None else now
                w = self._bucket(rule, client_key, now)
                return max(0, rule.limit - w.used)
        return None
