"""Header/body mutators (reference internal/headermutator,
internal/bodymutator): backend-level set/remove of request headers and
top-level JSON body fields, applied after translation, before auth."""

from __future__ import annotations

import json

from aigw_tpu.config.model import BodyMutation, HeaderMutation, _thaw


def apply_header_mutation(
    headers: dict[str, str], mutation: HeaderMutation
) -> dict[str, str]:
    if not mutation.set and not mutation.remove:
        return headers
    for name in mutation.remove:
        headers.pop(name, None)
    for name, value in mutation.set:
        headers[name] = value
    return headers


def apply_body_mutation(body: bytes, mutation: BodyMutation) -> bytes:
    if not mutation.set and not mutation.remove:
        return body
    try:
        data = json.loads(body)
    except json.JSONDecodeError:
        return body
    if not isinstance(data, dict):
        return body
    for name in mutation.remove:
        data.pop(name, None)
    for name, value in mutation.set:
        data[name] = _thaw(value)
    return json.dumps(data).encode()
