"""Upstream credential injection (reference internal/backendauth/auth.go:19-61).

Each handler implements ``apply(headers, body, path) -> (headers, path)``:
given the outgoing request headers (lowercase keys), the final serialized
body and the upstream path, it returns mutated headers (and possibly a
rewritten path — the GCP handler rewrites region/project placeholders).

Handlers must be retry-safe: they are re-applied from scratch on each
attempt (the reference re-signs per retry because SigV4 covers the body:
extproc/processor_impl.go:334-339).
"""

from aigw_tpu.gateway.auth.handlers import (
    AuthError,
    AuthHandler,
    new_handler,
)

__all__ = ["AuthError", "AuthHandler", "new_handler"]
