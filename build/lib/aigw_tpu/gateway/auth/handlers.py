"""Backend auth handler implementations.

Parity map to the reference (internal/backendauth):
- ``ApiKeyHandler``          ≈ apikey.go   (Authorization: Bearer)
- ``AnthropicApiKeyHandler`` ≈ anthropic key handling (x-api-key + version)
- ``AzureApiKeyHandler``     ≈ azure.go    (api-key header)
- ``AzureTokenHandler``      ≈ azure OIDC token (Authorization: Bearer)
- ``GcpTokenHandler``        ≈ gcp.go      (Bearer + {project}/{region} path rewrite)
- ``AwsSigV4Handler``        ≈ aws.go      (SigV4 signing incl. body SHA-256)

Credentials may be literals or ``file:<path>`` references; file-backed
secrets are re-read when the file changes (the reference's rotators update
mounted Secret files in place — controller/rotators/*).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import urllib.parse
from typing import Protocol

from aigw_tpu.config.model import AuthConfig, AuthKind


class AuthError(Exception):
    """Credential missing/invalid (reference ErrCredentialMissing → 401)."""


class AuthHandler(Protocol):
    def apply(
        self, headers: dict[str, str], body: bytes, path: str
    ) -> tuple[dict[str, str], str]: ...


class _Secret:
    """A literal or file-backed secret value with mtime-based refresh."""

    def __init__(self, value: str):
        self._path: str | None = None
        self._value = value
        self._mtime = 0.0
        if value.startswith("file:"):
            self._path = value[len("file:") :]
            self._value = ""

    def get(self) -> str:
        if self._path is None:
            return self._value
        try:
            mtime = os.stat(self._path).st_mtime
            if mtime != self._mtime or not self._value:
                with open(self._path, "r", encoding="utf-8") as f:
                    self._value = f.read().strip()
                self._mtime = mtime
        except OSError as e:
            raise AuthError(f"cannot read credential file {self._path}: {e}") from e
        return self._value


class NoopHandler:
    def apply(self, headers, body, path):
        return headers, path


class ApiKeyHandler:
    """Authorization: Bearer <key> (reference backendauth/apikey.go)."""

    def __init__(self, key: str):
        self._key = _Secret(key)

    def apply(self, headers, body, path):
        key = self._key.get()
        if not key:
            raise AuthError("API key credential missing")
        headers["authorization"] = f"Bearer {key}"
        return headers, path


class AnthropicApiKeyHandler:
    """x-api-key + anthropic-version headers."""

    def __init__(self, key: str, version: str):
        self._key = _Secret(key)
        self._version = version

    def apply(self, headers, body, path):
        key = self._key.get()
        if not key:
            raise AuthError("Anthropic API key credential missing")
        headers["x-api-key"] = key
        headers.setdefault("anthropic-version", self._version)
        headers.pop("authorization", None)
        return headers, path


class AzureApiKeyHandler:
    """api-key header (reference backendauth/azure.go)."""

    def __init__(self, key: str):
        self._key = _Secret(key)

    def apply(self, headers, body, path):
        key = self._key.get()
        if not key:
            raise AuthError("Azure API key credential missing")
        headers["api-key"] = key
        headers.pop("authorization", None)
        return headers, path


class BearerTokenHandler:
    """Authorization: Bearer <token> from a (possibly rotated) token file —
    used for Azure OIDC and plain OAuth backends."""

    def __init__(self, token: str):
        self._token = _Secret(token)

    def apply(self, headers, body, path):
        tok = self._token.get()
        if not tok:
            raise AuthError("bearer token credential missing")
        headers["authorization"] = f"Bearer {tok}"
        return headers, path


class GcpTokenHandler:
    """Bearer token plus `{GCP_PROJECT}`/`{GCP_REGION}` path substitution
    (the reference rewrites the Vertex path with project/region,
    backendauth/gcp.go + gcpauth)."""

    def __init__(self, token: str, project: str, region: str):
        self._token = _Secret(token)
        self._project = project
        self._region = region

    def apply(self, headers, body, path):
        tok = self._token.get()
        if not tok:
            raise AuthError("GCP access token credential missing")
        headers["authorization"] = f"Bearer {tok}"
        path = path.replace("{GCP_PROJECT}", self._project).replace(
            "{GCP_REGION}", self._region
        )
        return headers, path


class AwsSigV4Handler:
    """AWS Signature V4 request signing (reference backendauth/aws.go).

    Signs method, canonical path/query, host, x-amz-date, x-amz-security-token
    (if present) and the SHA-256 of the final body — which is why the
    gateway re-applies auth after every retranslation/retry.
    """

    _SIGNED_HEADERS = ("host", "x-amz-date", "x-amz-security-token")

    def __init__(
        self,
        access_key_id: str,
        secret_access_key: str,
        session_token: str,
        region: str,
        service: str,
    ):
        self._akid = _Secret(access_key_id)
        self._secret = _Secret(secret_access_key)
        self._session = _Secret(session_token) if session_token else None
        self._region = region
        self._service = service

    def apply(self, headers, body, path):
        akid, secret = self._akid.get(), self._secret.get()
        if not akid or not secret:
            raise AuthError("AWS credentials missing")
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        headers["x-amz-date"] = amz_date
        if self._session is not None:
            tok = self._session.get()
            if tok:
                headers["x-amz-security-token"] = tok

        parsed = urllib.parse.urlsplit(path)
        canonical_uri = urllib.parse.quote(parsed.path or "/", safe="/-_.~")
        query_pairs = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
        canonical_query = "&".join(
            f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
            for k, v in sorted(query_pairs)
        )
        present = [h for h in self._SIGNED_HEADERS if h in headers]
        canonical_headers = "".join(f"{h}:{headers[h].strip()}\n" for h in present)
        signed_headers = ";".join(present)
        payload_hash = hashlib.sha256(body or b"").hexdigest()
        canonical_request = "\n".join(
            (
                "POST",
                canonical_uri,
                canonical_query,
                canonical_headers,
                signed_headers,
                payload_hash,
            )
        )
        scope = f"{datestamp}/{self._region}/{self._service}/aws4_request"
        string_to_sign = "\n".join(
            (
                "AWS4-HMAC-SHA256",
                amz_date,
                scope,
                hashlib.sha256(canonical_request.encode()).hexdigest(),
            )
        )

        def _hmac(key: bytes, msg: str) -> bytes:
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k_date = _hmac(b"AWS4" + secret.encode(), datestamp)
        k_region = _hmac(k_date, self._region)
        k_service = _hmac(k_region, self._service)
        k_signing = _hmac(k_service, "aws4_request")
        signature = hmac.new(
            k_signing, string_to_sign.encode(), hashlib.sha256
        ).hexdigest()
        headers["authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={akid}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"
        )
        return headers, path


def new_handler(auth: AuthConfig) -> AuthHandler:
    """Dispatch on auth kind (reference backendauth.NewHandler, auth.go:19-61)."""
    k = auth.kind
    if k is AuthKind.NONE:
        return NoopHandler()
    if k is AuthKind.API_KEY:
        return ApiKeyHandler(auth.api_key)
    if k is AuthKind.ANTHROPIC_API_KEY:
        return AnthropicApiKeyHandler(auth.api_key, auth.anthropic_version)
    if k is AuthKind.AZURE_API_KEY:
        return AzureApiKeyHandler(auth.azure_api_key or auth.api_key)
    if k is AuthKind.AZURE_TOKEN:
        return BearerTokenHandler(auth.azure_access_token)
    if k is AuthKind.GCP_TOKEN:
        return GcpTokenHandler(auth.gcp_access_token, auth.gcp_project, auth.gcp_region)
    if k is AuthKind.AWS_SIGV4:
        return AwsSigV4Handler(
            auth.aws_access_key_id,
            auth.aws_secret_access_key,
            auth.aws_session_token,
            auth.aws_region,
            auth.aws_service,
        )
    raise AuthError(f"unsupported auth kind {k}")
