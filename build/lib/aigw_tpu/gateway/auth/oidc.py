"""OIDC → cloud-credential exchange (background rotation).

Equivalent of the reference's credential rotators + token providers
(internal/controller/rotators/{aws_oidc_rotator.go:198,
gcp_oidc_token_rotator.go:400, azure_token_rotator.go:143},
tokenprovider/oidc_token_provider.go:113): a client-credentials OIDC token
is exchanged for provider credentials which are refreshed in the
background before expiry and exposed to the auth handlers.

Flows:
- ``OIDCTokenProvider``   — client_credentials grant against a token URL
- ``AWSOIDCExchanger``    — STS ``AssumeRoleWithWebIdentity`` (XML)
- ``GCPOIDCExchanger``    — GCP STS token exchange (+ optional service
                            account impersonation)
- ``AzureOIDCExchanger``  — AAD client_credentials for a scope

All HTTP targets are configurable, so tests drive them against local fake
servers (no egress).
"""

from __future__ import annotations

import asyncio
import logging
import os
import re
import time
from dataclasses import dataclass, field
from typing import Any

import aiohttp

logger = logging.getLogger(__name__)


@dataclass
class Credential:
    value: dict[str, str]
    expires_at: float  # epoch seconds


class OIDCTokenProvider:
    """client_credentials grant → (access|id) token."""

    def __init__(self, token_url: str, client_id: str, client_secret: str,
                 scope: str = "openid"):
        self.token_url = token_url
        self.client_id = client_id
        self.client_secret = client_secret
        self.scope = scope

    async def fetch(self, session: aiohttp.ClientSession) -> Credential:
        async with session.post(
            self.token_url,
            data={
                "grant_type": "client_credentials",
                "client_id": self.client_id,
                "client_secret": self.client_secret,
                "scope": self.scope,
            },
        ) as resp:
            if resp.status != 200:
                raise RuntimeError(
                    f"OIDC token endpoint returned {resp.status}"
                )
            data = await resp.json()
        token = data.get("id_token") or data.get("access_token", "")
        ttl = float(data.get("expires_in", 3600))
        return Credential({"token": token}, time.time() + ttl)


class AWSOIDCExchanger:
    """OIDC token → STS AssumeRoleWithWebIdentity temporary keys."""

    def __init__(self, provider: OIDCTokenProvider, role_arn: str,
                 sts_url: str = "https://sts.amazonaws.com",
                 session_name: str = "aigw-tpu"):
        self.provider = provider
        self.role_arn = role_arn
        self.sts_url = sts_url
        self.session_name = session_name

    async def fetch(self, session: aiohttp.ClientSession) -> Credential:
        oidc = await self.provider.fetch(session)
        # form-encoded POST body (never the URL: the bearer token must
        # not land in proxy/server access logs)
        params = {
            "Action": "AssumeRoleWithWebIdentity",
            "Version": "2011-06-15",
            "RoleArn": self.role_arn,
            "RoleSessionName": self.session_name,
            "WebIdentityToken": oidc.value["token"],
        }
        async with session.post(self.sts_url + "/", data=params) as resp:
            text = await resp.text()
            if resp.status != 200:
                raise RuntimeError(f"STS returned {resp.status}: {text[:200]}")

        def xml(tag: str) -> str:
            m = re.search(rf"<{tag}>([^<]+)</{tag}>", text)
            return m.group(1) if m else ""

        expiry = xml("Expiration")
        expires_at = time.time() + 3600
        if expiry:
            try:
                from datetime import datetime, timezone

                expires_at = datetime.fromisoformat(
                    expiry.replace("Z", "+00:00")
                ).timestamp()
            except ValueError:
                pass
        return Credential(
            {
                "aws_access_key_id": xml("AccessKeyId"),
                "aws_secret_access_key": xml("SecretAccessKey"),
                "aws_session_token": xml("SessionToken"),
            },
            expires_at,
        )


class GCPOIDCExchanger:
    """OIDC token → GCP STS federated token (→ optional SA impersonation)."""

    def __init__(self, provider: OIDCTokenProvider, audience: str,
                 sts_url: str = "https://sts.googleapis.com/v1/token",
                 impersonate_url: str = ""):
        self.provider = provider
        self.audience = audience
        self.sts_url = sts_url
        self.impersonate_url = impersonate_url

    async def fetch(self, session: aiohttp.ClientSession) -> Credential:
        oidc = await self.provider.fetch(session)
        async with session.post(
            self.sts_url,
            json={
                "grantType": (
                    "urn:ietf:params:oauth:grant-type:token-exchange"
                ),
                "audience": self.audience,
                "requestedTokenType": (
                    "urn:ietf:params:oauth:token-type:access_token"
                ),
                "subjectToken": oidc.value["token"],
                "subjectTokenType": (
                    "urn:ietf:params:oauth:token-type:jwt"
                ),
                "scope": "https://www.googleapis.com/auth/cloud-platform",
            },
        ) as resp:
            if resp.status != 200:
                raise RuntimeError(f"GCP STS returned {resp.status}")
            data = await resp.json()
        token = data.get("access_token", "")
        ttl = float(data.get("expires_in", 3600))
        expires_at = time.time() + ttl
        if self.impersonate_url:
            async with session.post(
                self.impersonate_url,
                headers={"authorization": f"Bearer {token}"},
                json={"scope": [
                    "https://www.googleapis.com/auth/cloud-platform"
                ]},
            ) as resp:
                if resp.status != 200:
                    raise RuntimeError(
                        f"SA impersonation returned {resp.status}"
                    )
                data = await resp.json()
            token = data.get("accessToken", token)
            # the SA token's own lifetime may be shorter than the
            # federated token's — honor the earlier expiry
            expire_time = data.get("expireTime", "")
            if expire_time:
                try:
                    from datetime import datetime

                    sa_exp = datetime.fromisoformat(
                        expire_time.replace("Z", "+00:00")
                    ).timestamp()
                    expires_at = min(expires_at, sa_exp)
                except ValueError:
                    pass
        return Credential({"gcp_access_token": token}, expires_at)


class AzureOIDCExchanger:
    """AAD client-credentials flow for a resource scope."""

    def __init__(self, token_url: str, client_id: str, client_secret: str,
                 scope: str = "https://cognitiveservices.azure.com/.default"):
        self._inner = OIDCTokenProvider(token_url, client_id, client_secret,
                                        scope)

    async def fetch(self, session: aiohttp.ClientSession) -> Credential:
        cred = await self._inner.fetch(session)
        return Credential({"azure_access_token": cred.value["token"]},
                          cred.expires_at)


class CredentialRotator:
    """Background refresh loop writing rotated credentials to files the
    auth handlers watch (``file:<path>`` secrets re-read on mtime change —
    the same mounted-Secret contract as the reference's rotators)."""

    #: refresh when under this fraction of lifetime remains
    REFRESH_MARGIN = 0.2

    def __init__(self, exchanger: Any, out_paths: dict[str, str],
                 min_interval: float = 30.0):
        self.exchanger = exchanger
        self.out_paths = out_paths  # credential key → file path
        self.min_interval = min_interval
        self.current: Credential | None = None
        self._task: asyncio.Task | None = None

    @staticmethod
    def _write_secret(path: str, value: str) -> None:
        """Atomic, owner-only write: a reader never sees a truncated file
        and other local users can't read the credential (0600)."""
        tmp = f"{path}.tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        try:
            os.write(fd, value.encode())
        finally:
            os.close(fd)
        os.replace(tmp, path)

    async def refresh_once(self, session: aiohttp.ClientSession) -> None:
        cred = await self.exchanger.fetch(session)
        # NOTE: the three AWS files still update one-by-one; the SigV4
        # handler re-reads each on its own mtime, so a request landing
        # mid-rotation could pair an old secret with a new key id. STS
        # keys overlap in validity, so the stale *pair* (until the last
        # file flips) stays consistent per file-read; to avoid a mixed
        # pair we write the dependent files in reverse dependency order
        # (session token, secret, then key id last).
        ordered = sorted(
            self.out_paths.items(),
            key=lambda kv: kv[0] != "aws_access_key_id",
            reverse=True,
        )
        for key, path in ordered:
            if key in cred.value:
                self._write_secret(path, cred.value[key])
        self.current = cred
        logger.info("rotated credentials (%s), valid for %.0fs",
                    ",".join(self.out_paths), cred.expires_at - time.time())

    async def start(self) -> None:
        self._task = asyncio.create_task(self._run(), name="cred-rotator")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=30)
        ) as session:
            while True:
                try:
                    await self.refresh_once(session)
                    ttl = self.current.expires_at - time.time()
                    delay = max(self.min_interval,
                                ttl * (1 - self.REFRESH_MARGIN))
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # keep last good credentials
                    logger.warning("credential rotation failed: %s", e)
                    delay = self.min_interval
                await asyncio.sleep(delay)
