"""Sensitive-data redaction for debug logs.

The reference redacts credential headers and (optionally) message content
from debug logs (extproc/server.go:457-609, endpointspec
RedactSensitiveInfoFromRequest, internal/redaction). Same policy here:

- credential headers are always masked;
- request/response *content* is replaced by length placeholders unless
  ``AIGW_LOG_SENSITIVE=true`` explicitly opts into full payloads.
"""

from __future__ import annotations

import os
from typing import Any

#: headers that carry credentials — always masked in logs
SENSITIVE_HEADERS = frozenset(
    {
        "authorization",
        "x-api-key",
        "api-key",
        "proxy-authorization",
        "cookie",
        "x-amz-security-token",
        "mcp-session-id",
    }
)

_CONTENT_FIELDS = ("messages", "prompt", "input", "system", "documents",
                   "query", "contents")


def log_sensitive_allowed() -> bool:
    return os.environ.get("AIGW_LOG_SENSITIVE", "").lower() == "true"


def redact_headers(headers: dict[str, str]) -> dict[str, str]:
    return {
        k: "[REDACTED]" if k.lower() in SENSITIVE_HEADERS else v
        for k, v in headers.items()
    }


def redact_body(body: Any) -> Any:
    """Replace content-bearing fields with size placeholders."""
    if log_sensitive_allowed():
        return body
    if not isinstance(body, dict):
        return body
    out = dict(body)
    for field in _CONTENT_FIELDS:
        if field in out:
            v = out[field]
            if isinstance(v, str):
                out[field] = f"[REDACTED {len(v)} chars]"
            elif isinstance(v, list):
                out[field] = f"[REDACTED {len(v)} items]"
            else:
                out[field] = "[REDACTED]"
    return out
