"""ctypes bindings for the C++ hot-loop helpers (native/sse_scan.cpp).

Loaded lazily; every caller has a pure-Python fallback so the framework
runs without the compiled library (build with ``make -C native``).
"""

from __future__ import annotations

import ctypes
import os

_LIB = None
_TRIED = False


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
        "native",
        "libaigw_native.so",
    )
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.aigw_sse_scan.restype = ctypes.c_int
        lib.aigw_sse_scan.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.aigw_es_scan.restype = ctypes.c_int
        lib.aigw_es_scan.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        _LIB = lib
    except (OSError, AttributeError):
        _LIB = None
    return _LIB


def available() -> bool:
    return _load() is not None


_MAX_EVENTS = 4096
_scan_out = None
_scan_tail = None


def sse_scan(buf: bytes) -> tuple[list[tuple[int, int]], int, bool] | None:
    """Returns ([(event_end, sep_len), ...], tail_offset, truncated) or
    None if the native library is unavailable. ``truncated`` is True when
    the event-count cap was hit and the tail may hold more events."""
    global _scan_out, _scan_tail
    lib = _load()
    if lib is None:
        return None
    if _scan_out is None:  # reuse one output buffer (not thread-shared:
        # each SSEParser runs on the event loop thread)
        _scan_out = (ctypes.c_int32 * (2 * _MAX_EVENTS))()
        _scan_tail = ctypes.c_size_t(0)
    out, tail = _scan_out, _scan_tail
    n = lib.aigw_sse_scan(buf, len(buf), out, _MAX_EVENTS,
                          ctypes.byref(tail))
    return (
        [(out[2 * i], out[2 * i + 1]) for i in range(n)],
        tail.value,
        n >= _MAX_EVENTS,
    )


_MAX_FRAMES = 1024
_es_out = None
_es_tail = None


def es_scan(buf: bytes):
    """AWS event-stream frame scan: returns
    ([(offset, total_len, headers_len), ...], tail, truncated), None when
    the native library is unavailable, or raises ValueError on CRC error —
    mirroring aigw_tpu/translate/eventstream.py semantics."""
    global _es_out, _es_tail
    lib = _load()
    if lib is None:
        return None
    if _es_out is None:
        _es_out = (ctypes.c_int32 * (3 * _MAX_FRAMES))()
        _es_tail = ctypes.c_size_t(0)
    out, tail = _es_out, _es_tail
    n = lib.aigw_es_scan(buf, len(buf), out, _MAX_FRAMES,
                         ctypes.byref(tail))
    if n < 0:
        raise ValueError("event-stream CRC/framing error")
    return (
        [(out[3 * i], out[3 * i + 1], out[3 * i + 2]) for i in range(n)],
        tail.value,
        n >= _MAX_FRAMES,
    )
