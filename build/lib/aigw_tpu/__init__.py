"""aigw_tpu — a TPU-native AI gateway + serving framework.

A brand-new framework with the capabilities of Envoy AI Gateway
(reference: envoyproxy/ai-gateway), re-designed TPU-first:

- ``aigw_tpu.config``    — declarative gateway config model + compiler
  (equivalent of the reference's ``internal/filterapi`` +
  controller-translate, see reference filterapi/filterconfig.go:25).
- ``aigw_tpu.schemas``   — provider API schemas (OpenAI, Anthropic, AWS
  Bedrock, GCP, Cohere) (reference internal/apischema).
- ``aigw_tpu.translate`` — request/response schema translation matrix
  (reference internal/translator/translator.go:42-77).
- ``aigw_tpu.gateway``   — the native data-plane server: two-phase
  processing (route pass + upstream pass), weighted/priority backend
  selection, retry/fallback, streaming SSE, credential injection, token
  cost accounting (reference internal/extproc/processor_impl.go).
- ``aigw_tpu.tpuserve``  — JAX/XLA continuous-batching inference engine
  with a paged KV cache, the self-hosted serving path terminating on TPU
  (the reference's vLLM/InferencePool role, re-imagined for TPU).
- ``aigw_tpu.models``    — model families (Llama, Mixtral) as pure
  functional JAX programs.
- ``aigw_tpu.ops``       — attention ops incl. Pallas TPU kernels.
- ``aigw_tpu.parallel``  — device mesh, shardings, collectives (TP/EP/
  DP/SP over ICI; the TPU equivalent of the reference's NCCL-free,
  XLA-collective design, SURVEY.md §2.9).
- ``aigw_tpu.obs``       — OTel GenAI metrics + tracing (reference
  internal/metrics, internal/tracing).
- ``aigw_tpu.mcp``       — MCP (Model Context Protocol) proxy
  (reference internal/mcpproxy).
"""

__version__ = "0.1.0"
