"""Cloud-hosted Anthropic backends: GCP Vertex and AWS Bedrock.

Both speak the Anthropic messages *body* schema with provider-specific
envelopes (reference pairs openai→gcpanthropic / openai→awsanthropic and
anthropic→{gcpanthropic,awsanthropic}, anthropic_helper.go):

- **Vertex**: POST ``…/publishers/anthropic/models/{model}:rawPredict``
  (``:streamRawPredict?alt=sse`` when streaming); body drops ``model`` and
  gains ``anthropic_version: vertex-2023-10-16``. Responses are plain
  Anthropic JSON / SSE.
- **Bedrock**: POST ``/model/{id}/invoke`` (``invoke-with-response-stream``
  when streaming); body drops ``model``/``stream`` and gains
  ``anthropic_version: bedrock-2023-05-31``. Streaming responses are AWS
  event-stream frames whose payloads are ``{"bytes": base64(anthropic
  event JSON)}`` — decoded here and re-encoded as Anthropic SSE so the
  existing state machines (OpenAI-front converter or Anthropic-front
  passthrough) consume them unchanged.
"""

from __future__ import annotations

import base64
import json
import urllib.parse
from typing import Any

from aigw_tpu.config.model import APISchemaName
from aigw_tpu.translate.base import (
    Endpoint,
    RequestTx,
    ResponseTx,
    register_translator,
)
from aigw_tpu.translate.eventstream import EventStreamParser
from aigw_tpu.translate.openai_anthropic import OpenAIToAnthropicChat
from aigw_tpu.translate.passthrough import AnthropicPassthrough
from aigw_tpu.translate.sse import SSEEvent

VERTEX_ANTHROPIC_VERSION = "vertex-2023-10-16"
BEDROCK_ANTHROPIC_VERSION = "bedrock-2023-05-31"


def _vertexify(tx: RequestTx) -> RequestTx:
    body = json.loads(tx.body)
    model = body.pop("model", "")
    stream = bool(body.pop("stream", False))
    body["anthropic_version"] = VERTEX_ANTHROPIC_VERSION
    verb = "streamRawPredict?alt=sse" if stream else "rawPredict"
    tx.body = json.dumps(body).encode()
    tx.path = (
        "/v1/projects/{GCP_PROJECT}/locations/{GCP_REGION}"
        f"/publishers/anthropic/models/{model}:{verb}"
    )
    return tx


def _bedrockify(tx: RequestTx) -> RequestTx:
    body = json.loads(tx.body)
    model = body.pop("model", "")
    stream = bool(body.pop("stream", False))
    body["anthropic_version"] = BEDROCK_ANTHROPIC_VERSION
    verb = "invoke-with-response-stream" if stream else "invoke"
    tx.body = json.dumps(body).encode()
    tx.path = f"/model/{urllib.parse.quote(model, safe='')}/{verb}"
    return tx


class _BedrockAnthropicStream:
    """Event-stream frames → Anthropic SSE bytes."""

    def __init__(self) -> None:
        self._es = EventStreamParser()

    def feed(self, chunk: bytes) -> bytes:
        out = bytearray()
        for msg in self._es.feed(chunk):
            if not msg.payload:
                continue
            try:
                wrapper = json.loads(msg.payload)
                inner = base64.b64decode(wrapper.get("bytes", ""))
                data = json.loads(inner)
            except (json.JSONDecodeError, ValueError):
                continue
            out += SSEEvent(event=data.get("type", ""),
                            data=json.dumps(data)).encode()
        return bytes(out)


class OpenAIToVertexAnthropic(OpenAIToAnthropicChat):
    def __init__(self, **kw: Any):
        # GCP-hosted Anthropic lacks structured-output support (reference
        # anthropic_helper.go isGCPBackend check): skip output_config.
        kw.setdefault("gcp_backend", True)
        super().__init__(**kw)

    def request(self, body: dict[str, Any]) -> RequestTx:
        return _vertexify(super().request(body))


class OpenAIToBedrockAnthropic(OpenAIToAnthropicChat):
    def __init__(self, **kw: Any):
        super().__init__(**kw)
        self._es_decode = _BedrockAnthropicStream()

    def request(self, body: dict[str, Any]) -> RequestTx:
        return _bedrockify(super().request(body))

    def response_body(self, chunk: bytes, end_of_stream: bool) -> ResponseTx:
        if self._stream:
            chunk = self._es_decode.feed(chunk)
        return super().response_body(chunk, end_of_stream)


class AnthropicToVertexAnthropic(AnthropicPassthrough):
    def request(self, body: dict[str, Any]) -> RequestTx:
        return _vertexify(super().request(body))


class AnthropicToBedrockAnthropic(AnthropicPassthrough):
    def __init__(self, **kw: Any):
        super().__init__(**kw)
        self._es_decode = _BedrockAnthropicStream()

    def request(self, body: dict[str, Any]) -> RequestTx:
        return _bedrockify(super().request(body))

    def response_body(self, chunk: bytes, end_of_stream: bool) -> ResponseTx:
        if self._stream:
            chunk = self._es_decode.feed(chunk)
        return super().response_body(chunk, end_of_stream)


def _f(cls):
    def make(*, model_name_override: str = "", stream: bool = False,
             **_: object):
        return cls(model_name_override=model_name_override, stream=stream)

    return make


# These override the plain-Anthropic registrations from openai_anthropic.py
# (correct path/envelope for the hosted variants).
register_translator(Endpoint.CHAT_COMPLETIONS, APISchemaName.OPENAI,
                    APISchemaName.GCP_ANTHROPIC, _f(OpenAIToVertexAnthropic))
register_translator(Endpoint.CHAT_COMPLETIONS, APISchemaName.OPENAI,
                    APISchemaName.AWS_ANTHROPIC, _f(OpenAIToBedrockAnthropic))
register_translator(Endpoint.MESSAGES, APISchemaName.ANTHROPIC,
                    APISchemaName.GCP_ANTHROPIC, _f(AnthropicToVertexAnthropic))
register_translator(Endpoint.MESSAGES, APISchemaName.ANTHROPIC,
                    APISchemaName.AWS_ANTHROPIC, _f(AnthropicToBedrockAnthropic))
