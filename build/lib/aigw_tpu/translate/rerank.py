"""Cohere /v2/rerank translators (reference endpointspec Cohere rerank +
apischema/cohere/rerank_v2.go)."""

from __future__ import annotations

import json
from typing import Any

from aigw_tpu.config.model import APISchemaName
from aigw_tpu.gateway.costs import TokenUsage
from aigw_tpu.schemas.openai import SchemaError
from aigw_tpu.translate.base import (
    Endpoint,
    RequestTx,
    ResponseTx,
    Translator,
    register_translator,
)


class CoherePassthroughRerank(Translator):
    """Cohere front → Cohere backend; mines billed-unit usage."""

    def __init__(self, *, model_name_override: str = "", **_: object):
        self._override = model_name_override

    def request(self, body: dict[str, Any]) -> RequestTx:
        if not isinstance(body.get("query"), str):
            raise SchemaError("rerank request needs a 'query' string")
        if not isinstance(body.get("documents"), list) or not body["documents"]:
            raise SchemaError("rerank request needs non-empty 'documents'")
        if self._override:
            body = dict(body, model=self._override)
        return RequestTx(body=json.dumps(body).encode(),
                         path=Endpoint.RERANK.value)

    def response_body(self, chunk: bytes, end_of_stream: bool) -> ResponseTx:
        if not end_of_stream:
            return ResponseTx(body=chunk)
        try:
            data = json.loads(chunk) if chunk else {}
        except json.JSONDecodeError:
            return ResponseTx(body=chunk)
        units = ((data.get("meta") or {}).get("billed_units") or {})
        usage = TokenUsage(
            input_tokens=int(units.get("input_tokens", 0) or 0),
            output_tokens=int(units.get("output_tokens", 0) or 0),
            total_tokens=int(units.get("input_tokens", 0) or 0)
            + int(units.get("output_tokens", 0) or 0),
        )
        return ResponseTx(body=chunk, usage=usage,
                          model=str(data.get("model", "") or ""))


register_translator(
    Endpoint.RERANK, APISchemaName.COHERE, APISchemaName.COHERE,
    CoherePassthroughRerank,
)
