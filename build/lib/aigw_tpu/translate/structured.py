"""Structured-output (response_format / json_schema) conversion helpers.

Reference: internal/translator/jsonschema_helper.go:1-624 — $ref
dereferencing with circular-reference and recursion-depth guards, plus the
Gemini (GAPIC) schema conversion: allowed-field filtering,
``type: [T, "null"]`` → ``nullable: true``, single-element ``allOf``
collapse, and ``anyOf`` flattening with null-branch extraction.

Also parses the OpenAI ``response_format`` union (reference
apischema/openai ChatCompletionResponseFormat*) into a normalized form the
translators consume.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

MAX_RECURSION_DEPTH = 100


class JSONSchemaError(ValueError):
    """Invalid json_schema in response_format (client-facing 400)."""


# ---------------------------------------------------------------------------
# response_format parsing
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ResponseFormat:
    """Normalized OpenAI response_format."""

    kind: str  # "text" | "json_object" | "json_schema"
    schema: dict[str, Any] | None = None
    name: str = ""
    strict: bool = False


def parse_response_format(body: dict[str, Any]) -> ResponseFormat | None:
    """Validate + normalize ``body["response_format"]``; None if absent.

    Raises JSONSchemaError on malformed input (the reference 400s via
    strict union unmarshalling in apischema/openai)."""
    rf = body.get("response_format")
    if rf is None:
        return None
    if not isinstance(rf, dict):
        raise JSONSchemaError("response_format must be an object")
    kind = rf.get("type")
    if kind in ("text", "json_object"):
        return ResponseFormat(kind=kind)
    if kind != "json_schema":
        raise JSONSchemaError(
            f"response_format.type must be one of 'text', 'json_object', "
            f"'json_schema'; got {kind!r}"
        )
    js = rf.get("json_schema")
    if not isinstance(js, dict):
        raise JSONSchemaError(
            "response_format.json_schema must be an object")
    schema = js.get("schema")
    if schema is not None and not isinstance(schema, dict):
        raise JSONSchemaError(
            "response_format.json_schema.schema must be an object")
    return ResponseFormat(
        kind="json_schema",
        schema=schema,
        name=str(js.get("name", "") or ""),
        strict=bool(js.get("strict", False)),
    )


# ---------------------------------------------------------------------------
# $ref dereferencing (jsonSchemaDereference, helper.go:333)
# ---------------------------------------------------------------------------


def _retrieve_ref(path: str, schema: dict[str, Any]) -> Any:
    if not path.startswith("#/"):
        raise JSONSchemaError(
            f"ref paths must start with '#/', got: {path}")
    components = path.split("/")[1:]
    current: Any = schema
    for i, comp in enumerate(components):
        if not comp:
            raise JSONSchemaError(
                f"ref path contains empty component at position {i + 1}")
        if ".." in comp or "./" in comp:
            raise JSONSchemaError(
                f"ref path contains invalid characters: {comp}")
        if not isinstance(current, dict) or comp not in current:
            raise JSONSchemaError(
                f"reference {path!r} not found: component {comp!r} "
                "does not exist")
        current = current[comp]
    import copy

    return copy.deepcopy(current)


#: definition-container keys that hold referenced-only subschemas: they are
#: left un-dereferenced in place (consumers strip them). Only these may be
#: skipped — skipping arbitrary first-path components (e.g. a ref into
#: '#/properties/a') would exempt every same-named key from dereferencing.
_DEFINITION_CONTAINERS = frozenset({"$defs", "definitions"})


def _skip_keys(obj: Any, full: dict[str, Any], seen: set[str],
               depth: int) -> list[str]:
    """Definition-container keys reachable via $ref (e.g. '$defs') —
    left in place during dereferencing, dropped by consumers."""
    if depth >= MAX_RECURSION_DEPTH:
        raise JSONSchemaError(f"maximum recursion depth exceeded: {depth}")
    keys: list[str] = []
    if isinstance(obj, dict):
        for k, v in obj.items():
            if k == "$ref":
                if not isinstance(v, str):
                    raise JSONSchemaError("'$ref' value must be a string")
                if v in seen:
                    raise JSONSchemaError(
                        f"circular reference detected: {v}")
                seen.add(v)
                ref = _retrieve_ref(v, full)
                comps = v.split("/")
                if len(comps) > 1 and comps[1] in _DEFINITION_CONTAINERS:
                    keys.append(comps[1])
                keys.extend(_skip_keys(ref, full, seen, depth + 1))
                seen.discard(v)
            elif isinstance(v, (dict, list)):
                keys.extend(_skip_keys(v, full, seen, depth + 1))
    elif isinstance(obj, list):
        for el in obj:
            keys.extend(_skip_keys(el, full, seen, depth + 1))
    return keys


def _deref(obj: Any, full: dict[str, Any], skip: list[str],
           seen: set[str], depth: int) -> Any:
    if depth >= MAX_RECURSION_DEPTH:
        raise JSONSchemaError(f"maximum recursion depth exceeded: {depth}")
    if isinstance(obj, dict):
        out: dict[str, Any] = {}
        for k, v in obj.items():
            if k in skip:
                out[k] = v
                continue
            if k == "$ref":
                if not isinstance(v, str):
                    raise JSONSchemaError("'$ref' value must be a string")
                if v in seen:
                    raise JSONSchemaError(
                        f"circular reference detected: {v}")
                seen.add(v)
                ref = _retrieve_ref(v, full)
                resolved = _deref(ref, full, skip, seen, depth + 1)
                seen.discard(v)
                return resolved
            if isinstance(v, (dict, list)):
                out[k] = _deref(v, full, skip, seen, depth + 1)
            else:
                out[k] = v
        return out
    if isinstance(obj, list):
        return [_deref(el, full, skip, seen, depth + 1) for el in obj]
    return obj


def dereference(schema: dict[str, Any]) -> Any:
    """Substitute every ``$ref`` in a JSON Schema (circular-safe)."""
    if schema is None:
        raise JSONSchemaError("schema object cannot be None")
    skip = _skip_keys(schema, schema, set(), 0)
    return _deref(schema, schema, skip, set(), 0)


# ---------------------------------------------------------------------------
# Gemini (GAPIC) schema conversion (jsonSchemaToGemini, helper.go:567)
# ---------------------------------------------------------------------------

#: fields genai.Schema supports (helper.go:585-608)
GEMINI_ALLOWED_FIELDS = frozenset({
    "anyOf", "default", "description", "enum", "example", "format",
    "items", "maxItems", "maxLength", "maxProperties", "maximum",
    "minItems", "minLength", "minProperties", "minimum", "nullable",
    "pattern", "properties", "propertyOrdering", "required", "title",
    "type",
})


def _type_field(value: Any) -> dict[str, Any]:
    if isinstance(value, list):
        if len(value) != 2:
            raise JSONSchemaError(
                f"if type is a list, length must be 2, got {len(value)}")
        has_null = "null" in value
        non_null = next((t for t in value if t != "null"), None)
        if not has_null or non_null is None:
            raise JSONSchemaError(
                "if type is a list, it must contain one non-null type "
                "and 'null'")
        if isinstance(non_null, dict):
            raise JSONSchemaError("unexpected map type in type array")
        return {"type": str(non_null), "nullable": True}
    if isinstance(value, str):
        return {"type": value}
    raise JSONSchemaError(
        f"'type' must be a list or string, got {type(value).__name__}")


def _to_gapic(schema: dict[str, Any]) -> dict[str, Any]:
    out: dict[str, Any] = {}
    for key, value in schema.items():
        if key in _DEFINITION_CONTAINERS:
            continue
        if key == "$ref":
            # a $ref that survived dereferencing would silently become an
            # accept-anything schema — fail loudly instead
            raise JSONSchemaError(
                f"unresolved $ref in schema: {value!r}")
        if key == "items":
            if not isinstance(value, dict):
                raise JSONSchemaError(
                    f"'items' must be a dict, got {type(value).__name__}")
            out["items"] = _to_gapic(value)
        elif key == "properties":
            if not isinstance(value, dict):
                raise JSONSchemaError(
                    f"'properties' must be a dict, "
                    f"got {type(value).__name__}")
            props = {}
            for pk, pv in value.items():
                if not isinstance(pv, dict):
                    raise JSONSchemaError(
                        f"property {pk!r} must be a dict, "
                        f"got {type(pv).__name__}")
                props[pk] = _to_gapic(pv)
            out["properties"] = props
        elif key == "type":
            out.update(_type_field(value))
        elif key == "allOf":
            if not isinstance(value, list) or not value:
                raise JSONSchemaError("'allOf' must be a non-empty list")
            if len(value) > 1:
                raise JSONSchemaError(
                    f"only one value for 'allOf' key is supported, "
                    f"got {len(value)}")
            if not isinstance(value[0], dict):
                raise JSONSchemaError("item in 'allOf' must be an object")
            return _to_gapic(value[0])
        elif key == "anyOf":
            if not isinstance(value, list) or not value:
                raise JSONSchemaError("'anyOf' must be a non-empty list")
            branches = []
            nullable = False
            for i, v in enumerate(value):
                if not isinstance(v, dict):
                    raise JSONSchemaError(
                        f"item {i} in 'anyOf' must be a dict")
                if v.get("type") == "null":
                    nullable = True
                else:
                    branches.append(_to_gapic(v))
            if nullable:
                out["nullable"] = True
            out["anyOf"] = branches
        elif key in GEMINI_ALLOWED_FIELDS:
            out[key] = value
        # unknown fields are dropped (reference: not in allowed set)
    return out


def to_gemini_schema(schema: dict[str, Any]) -> dict[str, Any]:
    """JSON Schema → Gemini responseSchema dict (dereference + filter)."""
    deref = dereference(schema)
    if not isinstance(deref, dict):
        raise JSONSchemaError("dereferenced schema is not an object")
    return _to_gapic(deref)
