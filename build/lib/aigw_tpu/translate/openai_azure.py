"""OpenAI front → Azure OpenAI backend.

Azure speaks the OpenAI schema; the differences are the deployment-scoped
path and the api-version query parameter (reference openai→azureopenai
translator). The APISchema.version of the backend carries the api-version.
"""

from __future__ import annotations

import urllib.parse
from typing import Any

from aigw_tpu.config.model import APISchemaName
from aigw_tpu.schemas import openai as oai
from aigw_tpu.translate.base import Endpoint, RequestTx, register_translator
from aigw_tpu.translate.passthrough import PassthroughTranslator

DEFAULT_API_VERSION = "2025-01-01-preview"

_ENDPOINT_SUFFIX = {
    Endpoint.CHAT_COMPLETIONS: "chat/completions",
    Endpoint.COMPLETIONS: "completions",
    Endpoint.EMBEDDINGS: "embeddings",
    Endpoint.AUDIO_SPEECH: "audio/speech",
    Endpoint.AUDIO_TRANSCRIPTIONS: "audio/transcriptions",
    Endpoint.AUDIO_TRANSLATIONS: "audio/translations",
    Endpoint.IMAGES_GENERATIONS: "images/generations",
}


class OpenAIToAzure(PassthroughTranslator):
    def __init__(
        self,
        endpoint: Endpoint,
        *,
        model_name_override: str = "",
        stream: bool = False,
        out_version: str = "",
    ):
        super().__init__(
            path="",  # computed per request from the model/deployment
            usage_extractor=oai.extract_usage,
            model_name_override=model_name_override,
            stream=stream,
        )
        self._endpoint = endpoint
        self._api_version = out_version or DEFAULT_API_VERSION

    def request(self, body: dict[str, Any]) -> RequestTx:
        tx = super().request(body)
        deployment = urllib.parse.quote(
            self._override or oai.request_model(body), safe=""
        )
        suffix = _ENDPOINT_SUFFIX[self._endpoint]
        tx.path = (
            f"/openai/deployments/{deployment}/{suffix}"
            f"?api-version={self._api_version}"
        )
        return tx


def _install() -> None:
    for ep in _ENDPOINT_SUFFIX:
        def make(*, model_name_override: str = "", stream: bool = False,
                 out_version: str = "", _ep: Endpoint = ep):
            return OpenAIToAzure(
                _ep,
                model_name_override=model_name_override,
                stream=stream,
                out_version=out_version,
            )

        register_translator(ep, APISchemaName.OPENAI, APISchemaName.AZURE_OPENAI, make)


_install()
