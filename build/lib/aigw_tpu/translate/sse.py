"""Server-Sent Events incremental parsing and encoding.

The streaming hot loop (reference extproc processes SSE per-chunk in
ProcessResponseBody, processor_impl.go:481-575). The parser is incremental:
bytes arrive in arbitrary chunk boundaries from the upstream; events are
emitted as soon as their terminating blank line is seen, and leftover bytes
are buffered.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from aigw_tpu.utils import native as _native


@dataclass
class SSEEvent:
    data: str = ""
    event: str = ""
    id: str = ""
    retry: str = ""

    def encode(self) -> bytes:
        out = []
        if self.event:
            out.append(f"event: {self.event}")
        if self.id:
            out.append(f"id: {self.id}")
        if self.retry:
            out.append(f"retry: {self.retry}")
        for line in self.data.split("\n"):
            out.append(f"data: {line}")
        return ("\n".join(out) + "\n\n").encode()


@dataclass
class SSEParser:
    """Incremental SSE decoder; feed() returns completed events."""

    _buf: bytes = b""

    def feed(self, chunk: bytes) -> list[SSEEvent]:
        self._buf += chunk
        events: list[SSEEvent] = []
        # Fast path: the C++ scanner finds all boundaries in one pass
        # (native/sse_scan.cpp; byte-exact with the loop below).
        scan = _native.sse_scan(self._buf)
        if scan is not None:
            while True:
                boundaries, tail, truncated = scan
                start = 0
                for end, sep in boundaries:
                    ev = _parse_event(self._buf[start:end])
                    if ev is not None:
                        events.append(ev)
                    start = end + sep
                self._buf = self._buf[tail:]
                if not truncated:
                    return events
                scan = _native.sse_scan(self._buf)
        while True:
            # An event terminates at the first blank line.
            sep = -1
            for cand in (b"\n\n", b"\r\n\r\n"):
                i = self._buf.find(cand)
                if i != -1 and (sep == -1 or i < sep):
                    sep = i
                    seplen = len(cand)
            if sep == -1:
                break
            raw, self._buf = self._buf[:sep], self._buf[sep + seplen :]
            ev = _parse_event(raw)
            if ev is not None:
                events.append(ev)
        return events

    def flush(self) -> list[SSEEvent]:
        """Handle a final event not terminated by a blank line."""
        if not self._buf.strip():
            self._buf = b""
            return []
        ev = _parse_event(self._buf)
        self._buf = b""
        return [ev] if ev is not None else []


def _parse_event(raw: bytes) -> SSEEvent | None:
    ev = SSEEvent()
    data_lines: list[str] = []
    for line in raw.replace(b"\r\n", b"\n").split(b"\n"):
        if not line or line.startswith(b":"):
            continue
        name, _, value = line.partition(b":")
        if value.startswith(b" "):
            value = value[1:]
        text = value.decode("utf-8", errors="replace")
        fname = name.decode("ascii", errors="replace")
        if fname == "data":
            data_lines.append(text)
        elif fname == "event":
            ev.event = text
        elif fname == "id":
            ev.id = text
        elif fname == "retry":
            ev.retry = text
    ev.data = "\n".join(data_lines)
    if not ev.data and not ev.event:
        return None
    return ev
