"""AWS event-stream (application/vnd.amazon.eventstream) binary framing.

Bedrock streaming responses use this framing instead of SSE; the reference
re-encodes it to OpenAI SSE in its openai→awsbedrock translator. Frame
layout (big-endian):

    4B total length | 4B headers length | 4B prelude CRC32
    headers (name-len u8, name, type u8, value) ...
    payload
    4B message CRC32

Header value types: 7 = string (u16 length prefix). Other types are not
produced by Bedrock response streams but are skipped structurally.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field

from aigw_tpu.utils import native as _native


@dataclass
class EventStreamMessage:
    headers: dict[str, str]
    payload: bytes

    @property
    def event_type(self) -> str:
        return self.headers.get(":event-type", "")

    @property
    def exception_type(self) -> str:
        return self.headers.get(":exception-type", "")


class EventStreamParser:
    """Incremental decoder: feed() bytes, get complete messages."""

    def __init__(self) -> None:
        self._buf = b""

    def feed(self, chunk: bytes) -> list[EventStreamMessage]:
        self._buf += chunk
        out: list[EventStreamMessage] = []
        # native fast path: frame boundaries + CRCs validated in C++
        # (native/eventstream_scan.cpp); headers still parse in Python
        while True:
            scan = _native.es_scan(self._buf)
            if scan is None:
                break
            frames, tail, truncated = scan
            for off, total, hlen in frames:
                headers = _parse_headers(self._buf[off + 12 : off + 12 + hlen])
                payload = self._buf[off + 12 + hlen : off + total - 4]
                out.append(EventStreamMessage(headers=headers,
                                              payload=payload))
            self._buf = self._buf[tail:]
            if not truncated:
                return out
        while len(self._buf) >= 16:
            total_len, headers_len, prelude_crc = struct.unpack_from(
                ">III", self._buf
            )
            if len(self._buf) < total_len:
                break
            if zlib.crc32(self._buf[:8]) != prelude_crc:
                raise ValueError("event-stream prelude CRC mismatch")
            frame, self._buf = self._buf[:total_len], self._buf[total_len:]
            msg_crc = struct.unpack(">I", frame[-4:])[0]
            if zlib.crc32(frame[:-4]) != msg_crc:
                raise ValueError("event-stream message CRC mismatch")
            headers = _parse_headers(frame[12 : 12 + headers_len])
            payload = frame[12 + headers_len : -4]
            out.append(EventStreamMessage(headers=headers, payload=payload))
        return out


def _parse_headers(data: bytes) -> dict[str, str]:
    headers: dict[str, str] = {}
    i = 0
    while i < len(data):
        name_len = data[i]
        i += 1
        name = data[i : i + name_len].decode("utf-8")
        i += name_len
        vtype = data[i]
        i += 1
        if vtype == 7:  # string
            (vlen,) = struct.unpack_from(">H", data, i)
            i += 2
            headers[name] = data[i : i + vlen].decode("utf-8")
            i += vlen
        elif vtype in (0, 1):  # bool true/false — no value bytes
            headers[name] = "true" if vtype == 0 else "false"
        elif vtype == 2:  # byte
            headers[name] = str(data[i])
            i += 1
        elif vtype == 3:  # short
            headers[name] = str(struct.unpack_from(">h", data, i)[0])
            i += 2
        elif vtype == 4:  # integer
            headers[name] = str(struct.unpack_from(">i", data, i)[0])
            i += 4
        elif vtype in (5, 8):  # long / timestamp
            headers[name] = str(struct.unpack_from(">q", data, i)[0])
            i += 8
        elif vtype == 6:  # byte array
            (vlen,) = struct.unpack_from(">H", data, i)
            i += 2 + vlen
        elif vtype == 9:  # uuid
            i += 16
        else:
            raise ValueError(f"unknown event-stream header type {vtype}")
    return headers


def encode_message(headers: dict[str, str], payload: bytes) -> bytes:
    """Encode one event-stream frame (used by tests and the Bedrock fake)."""
    hdr = b""
    for name, value in headers.items():
        nb, vb = name.encode(), value.encode()
        hdr += struct.pack("B", len(nb)) + nb + b"\x07" + struct.pack(">H", len(vb)) + vb
    total = 12 + len(hdr) + len(payload) + 4
    prelude = struct.pack(">II", total, len(hdr))
    prelude_crc = struct.pack(">I", zlib.crc32(prelude))
    body = prelude + prelude_crc + hdr + payload
    return body + struct.pack(">I", zlib.crc32(body))
