"""Schema translation matrix (reference internal/translator/translator.go:42-77).

``get_translator(endpoint, in_schema, out_schema)`` returns a fresh stateful
translator per request. Streaming translators carry SSE re-encode state and
emit token-usage deltas per chunk, merged with override semantics.
"""

from aigw_tpu.translate.base import (
    Endpoint,
    RequestTx,
    ResponseTx,
    TranslationError,
    Translator,
    get_translator,
    register_translator,
    supported_pairs,
)

__all__ = [
    "Endpoint",
    "RequestTx",
    "ResponseTx",
    "TranslationError",
    "Translator",
    "get_translator",
    "register_translator",
    "supported_pairs",
]
