"""Anthropic /v1/messages front → OpenAI chat/completions backend.

Reverse direction of openai_anthropic (reference pair: anthropic→openai,
anthropic_helper.go). Lets Anthropic-SDK clients hit OpenAI-schema
backends — including the in-tree TPU engine.
"""

from __future__ import annotations

import json
import uuid
from typing import Any

from aigw_tpu.config.model import APISchemaName
from aigw_tpu.gateway.costs import TokenUsage
from aigw_tpu.schemas import anthropic as anth
from aigw_tpu.schemas import openai as oai
from aigw_tpu.translate.base import (
    Endpoint,
    RequestTx,
    ResponseTx,
    TranslationError,
    Translator,
    register_translator,
)
from aigw_tpu.translate.sse import SSEEvent, SSEParser


def anthropic_messages_to_openai(
    system: Any, messages: list[dict[str, Any]]
) -> list[dict[str, Any]]:
    out: list[dict[str, Any]] = []
    if system:
        text = (
            system
            if isinstance(system, str)
            else anth.text_of_blocks(anth.content_blocks(system))
        )
        if text:
            out.append({"role": "system", "content": text})
    for m in messages:
        role = m.get("role")
        blocks = anth.content_blocks(m.get("content"))
        if role == "system":
            # mid-conversation system message → OpenAI system message in
            # place (array position preserved)
            text = anth.text_of_blocks(blocks)
            if text:
                out.append({"role": "system", "content": text})
        elif role == "user":
            texts: list[str] = []
            for b in blocks:
                btype = b.get("type")
                if btype == "text":
                    texts.append(b.get("text", ""))
                elif btype == "tool_result":
                    content = b.get("content")
                    if isinstance(content, list):
                        content = anth.text_of_blocks(content)
                    out.append(
                        {
                            "role": "tool",
                            "tool_call_id": b.get("tool_use_id", ""),
                            "content": content or "",
                        }
                    )
                elif btype == "image":
                    src = b.get("source") or {}
                    if src.get("type") == "base64":
                        url = (
                            f"data:{src.get('media_type', 'image/png')};base64,"
                            f"{src.get('data', '')}"
                        )
                    else:
                        url = src.get("url", "")
                    out.append(
                        {
                            "role": "user",
                            "content": [
                                {"type": "image_url", "image_url": {"url": url}}
                            ],
                        }
                    )
            if texts:
                out.append({"role": "user", "content": "".join(texts)})
        elif role == "assistant":
            msg: dict[str, Any] = {"role": "assistant"}
            text = anth.text_of_blocks(blocks)
            msg["content"] = text or None
            tool_calls = [
                {
                    "id": b.get("id", ""),
                    "type": "function",
                    "function": {
                        "name": b.get("name", ""),
                        "arguments": json.dumps(b.get("input", {})),
                    },
                }
                for b in blocks
                if b.get("type") == "tool_use"
            ]
            if tool_calls:
                msg["tool_calls"] = tool_calls
            out.append(msg)
        else:
            raise TranslationError(f"unsupported role {role!r}")
    return out


class AnthropicToOpenAIChat(Translator):
    def __init__(self, *, model_name_override: str = "", stream: bool = False):
        self._override = model_name_override
        self._stream = stream
        self._parser = SSEParser()
        self._id = f"msg_{uuid.uuid4().hex[:24]}"
        self._model = ""
        self._usage = TokenUsage()
        # streaming state machine
        self._started = False  # message_start sent
        self._text_block_open = False
        self._tool_block_open = False
        self._block_idx = -1
        self._finish: str | None = None
        self._done = False

    def request(self, body: dict[str, Any]) -> RequestTx:
        anth.validate_messages_request(body)
        self._stream = bool(body.get("stream", False))
        out: dict[str, Any] = {
            "model": self._override or body["model"],
            "messages": anthropic_messages_to_openai(
                body.get("system"), body["messages"]
            ),
            "max_tokens": int(body["max_tokens"]),
        }
        if body.get("temperature") is not None:
            out["temperature"] = float(body["temperature"])
        if body.get("top_p") is not None:
            out["top_p"] = float(body["top_p"])
        if body.get("stop_sequences"):
            out["stop"] = list(body["stop_sequences"])
        tools = body.get("tools")
        if tools:
            out["tools"] = [
                {
                    "type": "function",
                    "function": {
                        "name": t.get("name", ""),
                        "description": t.get("description", ""),
                        "parameters": t.get("input_schema", {"type": "object"}),
                    },
                }
                for t in tools
            ]
        choice = body.get("tool_choice")
        if isinstance(choice, dict):
            ctype = choice.get("type")
            if ctype == "auto":
                out["tool_choice"] = "auto"
            elif ctype == "any":
                out["tool_choice"] = "required"
            elif ctype == "none":
                out["tool_choice"] = "none"
            elif ctype == "tool":
                out["tool_choice"] = {
                    "type": "function",
                    "function": {"name": choice.get("name", "")},
                }
        if self._stream:
            out["stream"] = True
            out["stream_options"] = {"include_usage": True}
        return RequestTx(
            body=json.dumps(out).encode(),
            path=Endpoint.CHAT_COMPLETIONS.value,
            stream=self._stream,
        )

    # -- response ---------------------------------------------------------
    def response_body(self, chunk: bytes, end_of_stream: bool) -> ResponseTx:
        if self._stream:
            return self._stream_chunk(chunk, end_of_stream)
        if not end_of_stream:
            return ResponseTx()
        try:
            data = json.loads(chunk)
        except json.JSONDecodeError as e:
            raise TranslationError(f"invalid upstream JSON: {e}") from None
        usage = oai.extract_usage(data)
        choice = (data.get("choices") or [{}])[0]
        msg = choice.get("message") or {}
        blocks: list[dict[str, Any]] = []
        if msg.get("content"):
            blocks.append({"type": "text", "text": msg["content"]})
        for tc in msg.get("tool_calls") or ():
            fn = tc.get("function") or {}
            try:
                args = json.loads(fn.get("arguments") or "{}")
            except json.JSONDecodeError:
                args = {}
            blocks.append(
                {
                    "type": "tool_use",
                    "id": tc.get("id", ""),
                    "name": fn.get("name", ""),
                    "input": args,
                }
            )
        stop_reason = anth.FINISH_REASON_TO_ANTHROPIC.get(
            choice.get("finish_reason") or "stop", "end_turn"
        )
        model = str(data.get("model", "") or "")
        # Anthropic input_tokens excludes cached; ours came from OpenAI where
        # prompt includes cached — report prompt as-is (cache fields zero).
        out = anth.messages_response(
            model=model,
            content=blocks,
            stop_reason=stop_reason,
            usage=usage,
            response_id=self._id,
        )
        return ResponseTx(body=json.dumps(out).encode(), usage=usage, model=model)

    def _stream_chunk(self, chunk: bytes, end_of_stream: bool) -> ResponseTx:
        events = self._parser.feed(chunk)
        if end_of_stream:
            events += self._parser.flush()
        out = bytearray()
        tokens = 0
        for ev in events:
            if not ev.data:
                continue
            if ev.data.strip() == "[DONE]":
                out += self._finalize()
                continue
            try:
                data = json.loads(ev.data)
            except json.JSONDecodeError:
                continue
            self._model = str(data.get("model", "") or "") or self._model
            if data.get("usage"):
                self._usage = self._usage.merge_override(oai.extract_usage(data))
            if not self._started:
                out += self._event(
                    "message_start",
                    {
                        "type": "message_start",
                        "message": anth.messages_response(
                            model=self._model,
                            content=[],
                            stop_reason=None,  # type: ignore[arg-type]
                            usage=self._usage,
                            response_id=self._id,
                        ),
                    },
                )
                self._started = True
            for choice in data.get("choices", ()):
                delta = choice.get("delta") or {}
                if delta.get("content"):
                    if self._tool_block_open:
                        out += self._close_block()
                    if not self._text_block_open:
                        self._block_idx += 1
                        self._text_block_open = True
                        out += self._event(
                            "content_block_start",
                            {
                                "type": "content_block_start",
                                "index": self._block_idx,
                                "content_block": {"type": "text", "text": ""},
                            },
                        )
                    tokens += 1
                    out += self._event(
                        "content_block_delta",
                        {
                            "type": "content_block_delta",
                            "index": self._block_idx,
                            "delta": {
                                "type": "text_delta",
                                "text": delta["content"],
                            },
                        },
                    )
                for tc in delta.get("tool_calls") or ():
                    fn = tc.get("function") or {}
                    if fn.get("name") or tc.get("id"):
                        out += self._close_block()
                        self._block_idx += 1
                        self._tool_block_open = True
                        out += self._event(
                            "content_block_start",
                            {
                                "type": "content_block_start",
                                "index": self._block_idx,
                                "content_block": {
                                    "type": "tool_use",
                                    "id": tc.get("id", ""),
                                    "name": fn.get("name", ""),
                                    "input": {},
                                },
                            },
                        )
                    if fn.get("arguments"):
                        out += self._event(
                            "content_block_delta",
                            {
                                "type": "content_block_delta",
                                "index": self._block_idx,
                                "delta": {
                                    "type": "input_json_delta",
                                    "partial_json": fn["arguments"],
                                },
                            },
                        )
                if choice.get("finish_reason"):
                    self._finish = anth.FINISH_REASON_TO_ANTHROPIC.get(
                        choice["finish_reason"], "end_turn"
                    )
        if end_of_stream and not self._done:
            out += self._finalize()
        usage = TokenUsage()
        if self._done:
            usage = self._usage
        return ResponseTx(
            body=bytes(out), usage=usage, model=self._model, tokens_emitted=tokens
        )

    def _close_block(self) -> bytes:
        if not (self._text_block_open or self._tool_block_open):
            return b""
        self._text_block_open = self._tool_block_open = False
        return self._event(
            "content_block_stop",
            {"type": "content_block_stop", "index": self._block_idx},
        )

    def _finalize(self) -> bytes:
        if self._done:
            return b""
        self._done = True
        out = bytearray()
        out += self._close_block()
        out += self._event(
            "message_delta",
            {
                "type": "message_delta",
                "delta": {
                    "stop_reason": self._finish or "end_turn",
                    "stop_sequence": None,
                },
                # include input_tokens so streaming clients can bill
            # correctly even though usage arrives at end-of-stream from
            # the OpenAI upstream (message_start carried zeros).
            "usage": {
                "input_tokens": self._usage.input_tokens,
                "output_tokens": self._usage.output_tokens,
            },
            },
        )
        out += self._event("message_stop", {"type": "message_stop"})
        return bytes(out)

    def _event(self, name: str, payload: dict[str, Any]) -> bytes:
        return SSEEvent(event=name, data=json.dumps(payload)).encode()

    def response_error(self, status: int, body: bytes) -> bytes:
        text = body.decode("utf-8", errors="replace")[:4096]
        return anth.error_body(
            f"upstream error (status {status}): {text}", type_="api_error"
        )


def _factory(*, model_name_override: str = "", stream: bool = False, **_: object):
    return AnthropicToOpenAIChat(
        model_name_override=model_name_override, stream=stream
    )


register_translator(
    Endpoint.MESSAGES, APISchemaName.ANTHROPIC, APISchemaName.OPENAI, _factory
)
# The in-tree TPU engine speaks the OpenAI surface; Anthropic-front traffic
# to it goes through the same mapping.
register_translator(
    Endpoint.MESSAGES, APISchemaName.ANTHROPIC, APISchemaName.TPUSERVE, _factory
)
