"""Partition specs for model states (Megatron-style TP via GSPMD).

Column-parallel in-projections (wq/wk/wv, w_gate/w_up) shard their output
dimension over ``tp``; row-parallel out-projections (wo, w_down) shard
their input dimension, so each layer needs exactly ONE all-reduce after
attention and one after the MLP — which GSPMD inserts automatically from
these specs (the "annotate shardings, let XLA insert collectives" recipe).

The paged KV cache shards on the KV-head axis over ``tp`` (Llama-3's 8 KV
heads ÷ TP=8 → one KV head per chip: cache reads/writes are fully local,
no collective in the decode hot loop).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from aigw_tpu.models.llama import LlamaConfig


def llama_param_specs(cfg: LlamaConfig) -> dict[str, P]:
    specs: dict[str, P] = {
        # vocab-sharded embedding + head (logits all-gathered by GSPMD)
        "embed": P("tp", None),
        "norm_f": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tp")
    for i in range(cfg.n_layers):
        specs[f"l{i}.attn_norm"] = P(None)
        specs[f"l{i}.wq"] = P(None, "tp")  # column parallel (heads)
        specs[f"l{i}.wk"] = P(None, "tp")
        specs[f"l{i}.wv"] = P(None, "tp")
        if getattr(cfg, "attn_bias", False):
            specs[f"l{i}.bq"] = P("tp")
            specs[f"l{i}.bk"] = P("tp")
            specs[f"l{i}.bv"] = P("tp")
        specs[f"l{i}.wo"] = P("tp", None)  # row parallel
        specs[f"l{i}.mlp_norm"] = P(None)
        specs[f"l{i}.w_gate"] = P(None, "tp")
        specs[f"l{i}.w_up"] = P(None, "tp")
        specs[f"l{i}.w_down"] = P("tp", None)
    return specs


def kv_cache_spec() -> P:
    """[L, 2, slots, n_kv_heads, head_dim] — shard KV heads over tp."""
    return P(None, None, None, "tp", None)


def shard_params(
    params: dict[str, jax.Array], cfg: LlamaConfig, mesh: Mesh
) -> dict[str, jax.Array]:
    """Place a host pytree onto the mesh with TP shardings."""
    specs = llama_param_specs(cfg)
    return {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))
        for k, v in params.items()
    }


def mixtral_param_specs(cfg) -> dict[str, P]:
    """Expert-parallel + tensor-parallel specs for the Mixtral family.

    Expert weights [E, D, F] shard experts over ``ep`` and the FFN width
    over ``tp``; GSPMD turns the dispatch/combine einsums in
    models/mixtral.py into all-to-alls over ``ep`` (SURVEY.md §2.9:
    "mesh axis for experts + all-to-all dispatch").
    """
    specs: dict[str, P] = {
        "embed": P("tp", None),
        "norm_f": P(None),
        "lm_head": P(None, "tp"),
    }
    for i in range(cfg.n_layers):
        specs[f"l{i}.attn_norm"] = P(None)
        specs[f"l{i}.wq"] = P(None, "tp")
        specs[f"l{i}.wk"] = P(None, "tp")
        specs[f"l{i}.wv"] = P(None, "tp")
        specs[f"l{i}.wo"] = P("tp", None)
        specs[f"l{i}.mlp_norm"] = P(None)
        specs[f"l{i}.gate"] = P(None, None)  # router: tiny, replicated
        specs[f"l{i}.w_gate"] = P("ep", None, "tp")
        specs[f"l{i}.w_up"] = P("ep", None, "tp")
        specs[f"l{i}.w_down"] = P("ep", "tp", None)
    return specs
