"""Mesh construction over ICI/DCN.

Axis conventions (scaling-book style):
- ``dp``  — data parallel: independent replicas / batch sharding
- ``tp``  — tensor parallel: attention heads + MLP columns over ICI
- ``sp``  — sequence/context parallel (ring attention over ICI neighbors)
- ``ep``  — expert parallel (MoE dispatch axis)

For serving on a single v5e-8 slice the default is a 1×8 (dp×tp) mesh; the
same code scales to multi-host by letting ``jax.distributed`` enumerate
devices across DCN (TP stays intra-slice so its collectives ride ICI).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh


@dataclass(frozen=True)
class MeshSpec:
    dp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.tp * self.sp * self.ep * self.pp

    @property
    def axis_names(self) -> tuple[str, ...]:
        return ("dp", "tp", "sp", "ep", "pp")


def make_mesh(spec: MeshSpec, devices: list | None = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if len(devices) < spec.size:
        raise ValueError(
            f"mesh {spec} needs {spec.size} devices, have {len(devices)}"
        )
    grid = np.asarray(devices[: spec.size]).reshape(
        spec.dp, spec.tp, spec.sp, spec.ep, spec.pp
    )
    return Mesh(grid, spec.axis_names)
