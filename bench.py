"""Round benchmark — prints ONE JSON line.

Headline: the BASELINE.json north star measured on the real chip —
continuous-batching engine decode throughput for **Llama-3-8B
architecture, W8A16 int8, batch 8, paged KV** (random weights:
throughput is weight-value-agnostic), plus TTFT. ``vs_baseline`` is the
engine / raw-JAX-decode-ceiling ratio for the same model — the "≥90% of
raw JAX tokens/sec" criterion. The raw ceiling is the best raw loop we
can write: a K-step ``lax.scan`` inside one jit (single-step dispatch
pays ~8ms/step of tunnel latency and would flatter the engine).

Falls back to a 1.1B bf16 llama-arch model when the 8B int8 model
doesn't fit the chip, and prints an honest zero when the TPU tunnel is
unresponsive (watchdog probe).

    {"metric": "...", "value": engine_tokens_per_sec, "unit": "tokens/s",
     "vs_baseline": engine/raw_ceiling, "ttft_ms_p50": ...}
"""

from __future__ import annotations

import json
import sys
import threading
import time

import jax
import jax.numpy as jnp

from aigw_tpu.models import llama
from aigw_tpu.tpuserve.engine import Engine, EngineConfig, GenRequest
from aigw_tpu.tpuserve.sampling import SamplingParams, sample

FALLBACK_CFG = llama.LlamaConfig(
    vocab_size=32000, dim=2048, n_layers=16, n_heads=16, n_kv_heads=8,
    ffn_dim=8192, max_seq_len=1024, rope_theta=500000.0,
)
BATCH = 8
PAGE = 128
PROMPT_LEN = 128
GEN_TOKENS = 128
K_STEPS = 16  # matches EngineConfig.decode_steps_per_tick below


def raw_ceiling_tokens_per_sec(params, cfg) -> float:
    """The ceiling: K decode steps scanned inside one jit — bare model
    math + sampling with dispatch fully amortized; no scheduler, no
    paging bookkeeping, no HTTP."""
    from jax import lax

    ecfg = EngineConfig(max_batch_size=BATCH, max_seq_len=cfg.max_seq_len,
                        page_size=PAGE)
    kv = jnp.zeros(
        (cfg.n_layers, 2, ecfg.num_pages * PAGE, cfg.n_kv_heads,
         cfg.head_dim), jnp.bfloat16,
    )
    pt = jnp.arange(BATCH * ecfg.max_pages_per_seq, dtype=jnp.int32).reshape(
        BATCH, ecfg.max_pages_per_seq
    )
    active = jnp.ones((BATCH,), bool)
    keys = jnp.zeros((BATCH, 2), jnp.uint32)
    temp = jnp.zeros((BATCH,), jnp.float32)
    top_p = jnp.ones((BATCH,), jnp.float32)
    top_k = jnp.zeros((BATCH,), jnp.int32)

    def kstep(params, tokens, positions, kv):
        def body(carry, _):
            tokens, positions, kv = carry
            logits, kv = llama.decode_step(
                params, cfg, tokens, positions, kv, pt, PAGE, active
            )
            nxt = sample(logits, keys, temp, top_p, top_k)
            return (nxt, positions + 1, kv), nxt

        (tokens, positions, kv), _ = lax.scan(
            body, (tokens, positions, kv), None, length=K_STEPS
        )
        return tokens, positions, kv

    kstep = jax.jit(kstep, donate_argnums=(3,))
    tokens = jnp.ones((BATCH,), jnp.int32)
    positions = jnp.full((BATCH,), PROMPT_LEN, jnp.int32)

    tokens, positions, kv = kstep(params, tokens, positions, kv)  # compile
    jax.block_until_ready(tokens)
    n_ticks = max(1, 64 // K_STEPS)
    best = 0.0
    for _ in range(2):  # two trials, keep the best (tunnel jitter)
        t0 = time.perf_counter()
        for _ in range(n_ticks):
            tokens, positions, kv = kstep(params, tokens, positions, kv)
        jax.block_until_ready(tokens)
        dt = time.perf_counter() - t0
        best = max(best, BATCH * K_STEPS * n_ticks / dt)
    return best


def engine_numbers(params, cfg) -> tuple[float, float]:
    """The product: same decode through the continuous-batching engine.
    Returns (tokens/sec, ttft_ms p50 over the batch)."""
    eng = Engine(
        params,
        cfg,
        EngineConfig(max_batch_size=BATCH,
                     max_seq_len=cfg.max_seq_len, page_size=PAGE,
                     decode_steps_per_tick=K_STEPS),
    )
    eng.start()
    try:
        eng.warmup()
        # warm the prefill bucket for PROMPT_LEN
        done = threading.Event()
        eng.submit(GenRequest(
            prompt=[1] * PROMPT_LEN, max_tokens=2,
            sampling=SamplingParams(temperature=0.0),
            emit=lambda t, f: done.set() if f else None,
        ))
        done.wait(timeout=600)

        dones = [threading.Event() for _ in range(BATCH)]
        counts = [0] * BATCH
        first_at = [0.0] * BATCH

        def mk(i):
            def emit(tok, fin):
                if tok >= 0:
                    if counts[i] == 0:
                        first_at[i] = time.perf_counter()
                    counts[i] += 1
                if fin is not None:
                    dones[i].set()
            return emit

        t0 = time.perf_counter()
        for i in range(BATCH):
            eng.submit(GenRequest(
                prompt=[1 + i] * PROMPT_LEN, max_tokens=GEN_TOKENS,
                sampling=SamplingParams(temperature=0.0), emit=mk(i),
            ))
        for d in dones:
            d.wait(timeout=600)
        dt = time.perf_counter() - t0
        ttfts = sorted((f - t0) * 1000.0 for f in first_at if f > 0)
        ttft_p50 = ttfts[len(ttfts) // 2] if ttfts else -1.0
        return sum(counts) / dt, ttft_p50
    finally:
        eng.stop()


def _chip_responsive(timeout_s: float = 180.0) -> bool:
    """The axon tunnel can go down entirely (observed 2026-07-28); probe
    with a watchdog so the bench prints an honest line instead of hanging
    the driver."""
    done = threading.Event()
    result = {"ok": False}

    def probe():
        try:
            x = jnp.ones((128, 128)) @ jnp.ones((128, 128))
            x.block_until_ready()
            result["ok"] = True
        except Exception as e:  # fail fast with the real reason
            result["error"] = f"{type(e).__name__}: {e}"
        finally:
            done.set()

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    done.wait(timeout_s)
    if not result["ok"] and "error" in result:
        print(f"device probe failed: {result['error']}", file=sys.stderr)
    return result["ok"]


def _build_8b_int8():
    from aigw_tpu.models.quant import quantize_params

    cfg = llama.LlamaConfig(max_seq_len=1024)  # LLAMA3_8B shapes
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    params = quantize_params(params, consume=True)
    jax.block_until_ready(params)
    return params, cfg, "llama-3-8b-arch W8A16 int8"


def _build_fallback():
    params = llama.init_params(jax.random.PRNGKey(0), FALLBACK_CFG)
    jax.block_until_ready(params)
    return params, FALLBACK_CFG, "1.1B llama-arch bf16"


def run_live() -> dict:
    """One full live measurement (assumes the chip answered the probe)."""
    try:
        params, cfg, desc = _build_8b_int8()
    except Exception as e:  # OOM on smaller chips → honest fallback
        print(f"8B int8 build failed ({type(e).__name__}: {e}), "
              f"falling back to 1.1B bf16", file=sys.stderr)
        params, cfg, desc = _build_fallback()
    raw = raw_ceiling_tokens_per_sec(params, cfg)
    engine, ttft_ms = engine_numbers(params, cfg)
    return {
        "metric": (
            f"decode tokens/sec/chip, {desc}, batch={BATCH}, "
            f"prompt={PROMPT_LEN}, paged KV (engine vs "
            f"raw-JAX-K-step-scan ceiling in vs_baseline)"
        ),
        "value": round(engine, 1),
        "unit": "tokens/s",
        "vs_baseline": round(engine / raw, 4),
        "raw_ceiling": round(raw, 1),
        "ttft_ms_p50": round(ttft_ms, 1),
    }


def main() -> None:
    from benchmarks import persist

    if _chip_responsive():
        result = run_live()
        # persist only real-chip runs: a CPU run (JAX_PLATFORMS=cpu dev
        # loop) passing the probe must not overwrite on-chip history
        if jax.default_backend() == "tpu":
            persist.save("headline", result)
        print(json.dumps(result))
        return
    # Tunnel down at bench time (it comes and goes): report the latest
    # measurement persisted by an earlier run this round rather than a
    # zero — with its age, so the number's provenance is explicit.
    prior = persist.latest("headline")
    if prior is not None:
        age = persist.age_hours(prior)
        result = dict(prior)
        result["metric"] = (
            f"{prior['metric']} — persisted measurement from "
            f"{prior.get('captured_at', '?')} "
            f"({age:.1f}h old; tunnel down at bench time)"
            if age is not None else prior["metric"]
        )
        print(json.dumps(result))
        return
    print(
        json.dumps(
            {
                "metric": (
                    "decode tokens/sec/chip — TPU tunnel unresponsive "
                    "at bench time and no persisted on-chip run exists "
                    "(device probe timed out)"
                ),
                "value": 0,
                "unit": "tokens/s",
                "vs_baseline": 0,
            }
        )
    )


if __name__ == "__main__":
    main()
