"""Round benchmark — prints ONE JSON line.

Measures the BASELINE.json north-star ratio on the real chip: continuous-
batching engine decode throughput vs the raw JAX decode-loop ceiling for
the same model/batch (the "≥90% of raw JAX tokens/sec" criterion), on a
~1.1B-parameter Llama-architecture model (random weights — throughput is
weight-agnostic) that fits a single v5e chip in bf16.

    {"metric": "...", "value": engine_tokens_per_sec, "unit": "tokens/s",
     "vs_baseline": engine/raw_jax}
"""

from __future__ import annotations

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from aigw_tpu.models import llama
from aigw_tpu.tpuserve.engine import Engine, EngineConfig, GenRequest
from aigw_tpu.tpuserve.sampling import SamplingParams, sample

BENCH_CFG = llama.LlamaConfig(
    vocab_size=32000, dim=2048, n_layers=16, n_heads=16, n_kv_heads=8,
    ffn_dim=8192, max_seq_len=1024, rope_theta=500000.0,
)
BATCH = 8
PAGE = 128
PROMPT_LEN = 128
GEN_TOKENS = 128


def raw_jax_tokens_per_sec(params) -> float:
    """The ceiling: bare jitted decode steps, no scheduler, no HTTP."""
    cfg = EngineConfig(max_batch_size=BATCH, max_seq_len=BENCH_CFG.max_seq_len,
                       page_size=PAGE)
    kv = jnp.zeros(
        (BENCH_CFG.n_layers, 2, cfg.num_pages * PAGE, BENCH_CFG.n_kv_heads,
         BENCH_CFG.head_dim), jnp.bfloat16,
    )
    pt = jnp.arange(BATCH * cfg.max_pages_per_seq, dtype=jnp.int32).reshape(
        BATCH, cfg.max_pages_per_seq
    )
    active = jnp.ones((BATCH,), bool)
    keys = jnp.zeros((BATCH, 2), jnp.uint32)
    temp = jnp.zeros((BATCH,), jnp.float32)
    top_p = jnp.ones((BATCH,), jnp.float32)
    top_k = jnp.zeros((BATCH,), jnp.int32)

    def step(params, tokens, positions, kv):
        logits, kv = llama.decode_step(
            params, BENCH_CFG, tokens, positions, kv, pt, PAGE, active
        )
        return sample(logits, keys, temp, top_p, top_k), kv

    step = jax.jit(step, donate_argnums=(3,))
    tokens = jnp.ones((BATCH,), jnp.int32)
    positions = jnp.full((BATCH,), PROMPT_LEN, jnp.int32)

    tokens, kv = step(params, tokens, positions, kv)  # compile
    jax.block_until_ready(tokens)
    n_steps = 64
    t0 = time.perf_counter()
    for i in range(n_steps):
        tokens, kv = step(params, tokens, positions + 1 + i, kv)
    jax.block_until_ready(tokens)
    dt = time.perf_counter() - t0
    return BATCH * n_steps / dt


def engine_tokens_per_sec(params) -> float:
    """The product: same decode through the continuous-batching engine."""
    eng = Engine(
        params,
        BENCH_CFG,
        EngineConfig(max_batch_size=BATCH,
                     max_seq_len=BENCH_CFG.max_seq_len, page_size=PAGE,
                     decode_steps_per_tick=16),
    )
    eng.start()
    try:
        eng.warmup()
        # warm the prefill bucket for PROMPT_LEN
        done = threading.Event()
        eng.submit(GenRequest(
            prompt=[1] * PROMPT_LEN, max_tokens=2,
            sampling=SamplingParams(temperature=0.0),
            emit=lambda t, f: done.set() if f else None,
        ))
        done.wait(timeout=300)

        dones = [threading.Event() for _ in range(BATCH)]
        counts = [0] * BATCH

        def mk(i):
            def emit(tok, fin):
                if tok >= 0:
                    counts[i] += 1
                if fin is not None:
                    dones[i].set()
            return emit

        t0 = time.perf_counter()
        for i in range(BATCH):
            eng.submit(GenRequest(
                prompt=[1 + i] * PROMPT_LEN, max_tokens=GEN_TOKENS,
                sampling=SamplingParams(temperature=0.0), emit=mk(i),
            ))
        for d in dones:
            d.wait(timeout=600)
        dt = time.perf_counter() - t0
        return sum(counts) / dt
    finally:
        eng.stop()


def _chip_responsive(timeout_s: float = 180.0) -> bool:
    """The axon tunnel can go down entirely (observed 2026-07-28); probe
    with a watchdog so the bench prints an honest line instead of hanging
    the driver."""
    import threading

    done = threading.Event()
    result = {"ok": False}

    def probe():
        try:
            x = jnp.ones((128, 128)) @ jnp.ones((128, 128))
            x.block_until_ready()
            result["ok"] = True
        except Exception as e:  # fail fast with the real reason
            result["error"] = f"{type(e).__name__}: {e}"
        finally:
            done.set()

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    done.wait(timeout_s)
    if not result["ok"] and "error" in result:
        print(f"device probe failed: {result['error']}",
              file=__import__("sys").stderr)
    return result["ok"]


def main() -> None:
    if not _chip_responsive():
        print(
            json.dumps(
                {
                    "metric": (
                        "decode tokens/sec/chip — TPU tunnel unresponsive at "
                        "bench time (device probe timed out; last recorded "
                        "run: 780-790 tok/s, vs_baseline 1.11-1.21, see "
                        "BASELINE.md)"
                    ),
                    "value": 0,
                    "unit": "tokens/s",
                    "vs_baseline": 0,
                }
            )
        )
        return
    params = llama.init_params(jax.random.PRNGKey(0), BENCH_CFG)
    jax.block_until_ready(params)
    raw = raw_jax_tokens_per_sec(params)
    engine = engine_tokens_per_sec(params)
    print(
        json.dumps(
            {
                "metric": (
                    "decode tokens/sec/chip, 1.1B llama-arch bf16, batch=8, "
                    "paged KV (engine vs raw-JAX-loop ratio in vs_baseline)"
                ),
                "value": round(engine, 1),
                "unit": "tokens/s",
                "vs_baseline": round(engine / raw, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
