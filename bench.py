"""Round benchmark — prints ONE JSON line.

Headline (round 4+): the BASELINE.json north star measured end to end —
**tokens/sec through the gateway**: `aigw run` (real CLI subprocess) in
front of the tpuserve engine, driven over streaming
`/v1/chat/completions`, for Llama-3-8B architecture W8A16 int8, batch 8,
paged KV (random weights: throughput is weight-value-agnostic).
``vs_baseline`` is gateway / raw-JAX-decode-ceiling — the "≥90% of raw
JAX tokens/sec **through the gateway**" criterion — and ``ttft_ms_p50``
is time-to-first-token at the HTTP surface (the "<200ms" criterion).
The engine-only row (round 1-3's headline) is kept as
``engine_tokens_per_sec`` / ``engine_vs_raw``.

The raw ceiling is the best raw loop we can write: a K-step ``lax.scan``
inside one jit (single-step dispatch pays ~8ms/step of tunnel latency
and would flatter the engine).

Falls back to a 1.1B bf16 llama-arch model when the 8B int8 model
doesn't fit the chip. When the TPU tunnel is unresponsive (watchdog
probe), reports the latest persisted on-chip run; failing that, a
CPU-backend gateway/raw ratio with honest labeling (the ratio harness is
chip-independent; only absolute tok/s needs the chip) via
``--cpu-gateway-ratio`` in a JAX_PLATFORMS=cpu subprocess.

    {"metric": "...", "value": gateway_tokens_per_sec, "unit": "tokens/s",
     "vs_baseline": gateway/raw_ceiling, "ttft_ms_p50": ...,
     "engine_tokens_per_sec": ..., "engine_vs_raw": ...}
"""

from __future__ import annotations

import asyncio
import gc
import json
import os
import socket
import subprocess
import sys
import threading
import time

import jax

# The axon sitecustomize re-applies JAX_PLATFORMS=axon even when the
# environment says cpu (see tests/conftest.py); config.update after
# import is the only override that sticks. Without this, CPU-ratio mode
# hangs forever dialing the dead TPU tunnel.
if ("--cpu-gateway-ratio" in sys.argv or "--ab" in sys.argv
        or os.environ.get("JAX_PLATFORMS", "") == "cpu"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

from aigw_tpu.models import llama, mixtral
from aigw_tpu.obs import slomon
from aigw_tpu.tpuserve.engine import Engine, EngineConfig, GenRequest
from aigw_tpu.tpuserve.sampling import SamplingParams, sample

FALLBACK_CFG = llama.LlamaConfig(
    vocab_size=32000, dim=2048, n_layers=16, n_heads=16, n_kv_heads=8,
    ffn_dim=8192, max_seq_len=1024, rope_theta=500000.0,
)
# CPU-ratio model: small enough that a full gateway→engine run finishes
# in minutes on the host; the gateway/raw *ratio* is what transfers.
CPU_CFG = llama.LlamaConfig(
    vocab_size=8192, dim=512, n_layers=4, n_heads=8, n_kv_heads=4,
    ffn_dim=1536, max_seq_len=512, rope_theta=10000.0,
)
BATCH = 8
PAGE = 128
PROMPT_LEN = 128
GEN_TOKENS = 128
K_STEPS = 16  # matches EngineConfig.decode_steps_per_tick below

#: chip peak FLOPs/s the MFU is normalized against. Default: TPU v5e
#: bf16 peak (197 TFLOP/s). Override per deployment with
#: AIGW_CHIP_PEAK_FLOPS; on the CPU backend the resulting MFU is a
#: diagnostic only (the denominator is still the chip peak so the
#: number is directly comparable once the same harness runs on-chip).
CHIP_PEAK_FLOPS = float(os.environ.get("AIGW_CHIP_PEAK_FLOPS", 197e12))


def model_flops_per_token(cfg, context: int) -> float:
    """Analytical decode FLOPs per generated token: 2 FLOPs per matmul
    weight touched per token (q/k/v/o projections, the 3 MLP matrices,
    lm_head — embedding lookups are gathers, not FLOPs) plus the
    attention score/value matmuls, 4·dim FLOPs per cached token per
    layer (QK^T and PV each 2·dim). The PaLM-appendix accounting,
    specialized to GQA shapes."""
    hd = cfg.head_dim
    per_layer = (
        cfg.dim * cfg.n_heads * hd        # wq
        + 2 * cfg.dim * cfg.n_kv_heads * hd  # wk, wv
        + cfg.n_heads * hd * cfg.dim      # wo
        + 3 * cfg.dim * cfg.ffn_dim       # w_gate, w_up, w_down
    )
    matmul_params = cfg.n_layers * per_layer + cfg.dim * cfg.vocab_size
    attn = 4.0 * cfg.n_layers * context * cfg.dim
    return 2.0 * matmul_params + attn


def model_mfu(cfg, tokens_per_sec: float, context: int,
              peak_flops: float = 0.0) -> float:
    """Model FLOPs utilization of a measured decode rate (VERDICT r5 #2:
    reported as a CPU diagnostic until the first on-chip capture)."""
    peak = peak_flops or CHIP_PEAK_FLOPS
    return tokens_per_sec * model_flops_per_token(cfg, context) / peak


def raw_ceiling_tokens_per_sec(params, cfg, batch=BATCH,
                               prompt_len=PROMPT_LEN,
                               k_steps=K_STEPS) -> float:
    """The ceiling: K decode steps scanned inside one jit — bare model
    math + sampling with dispatch fully amortized; no scheduler, no
    paging bookkeeping, no HTTP."""
    from jax import lax

    ecfg = EngineConfig(max_batch_size=batch, max_seq_len=cfg.max_seq_len,
                        page_size=PAGE)
    kv = jnp.zeros(
        (cfg.n_layers, 2, ecfg.num_pages * PAGE, cfg.n_kv_heads,
         cfg.head_dim), jnp.bfloat16,
    )
    pt = jnp.arange(batch * ecfg.max_pages_per_seq, dtype=jnp.int32).reshape(
        batch, ecfg.max_pages_per_seq
    )
    active = jnp.ones((batch,), bool)
    keys = jnp.zeros((batch, 2), jnp.uint32)
    temp = jnp.zeros((batch,), jnp.float32)
    top_p = jnp.ones((batch,), jnp.float32)
    top_k = jnp.zeros((batch,), jnp.int32)

    def kstep(params, tokens, positions, kv):
        def body(carry, _):
            tokens, positions, kv = carry
            logits, kv = llama.decode_step(
                params, cfg, tokens, positions, kv, pt, PAGE, active
            )
            nxt = sample(logits, keys, temp, top_p, top_k)
            return (nxt, positions + 1, kv), nxt

        (tokens, positions, kv), _ = lax.scan(
            body, (tokens, positions, kv), None, length=k_steps
        )
        return tokens, positions, kv

    kstep = jax.jit(kstep, donate_argnums=(3,))
    tokens = jnp.ones((batch,), jnp.int32)
    positions = jnp.full((batch,), prompt_len, jnp.int32)

    tokens, positions, kv = kstep(params, tokens, positions, kv)  # compile
    jax.block_until_ready(tokens)
    n_ticks = max(1, 64 // k_steps)
    best = 0.0
    for _ in range(2):  # two trials, keep the best (tunnel jitter)
        t0 = time.perf_counter()
        for _ in range(n_ticks):
            tokens, positions, kv = kstep(params, tokens, positions, kv)
        jax.block_until_ready(tokens)
        dt = time.perf_counter() - t0
        best = max(best, batch * k_steps * n_ticks / dt)
    return best


def engine_numbers(params, cfg, batch=BATCH, prompt_len=PROMPT_LEN,
                   gen_tokens=GEN_TOKENS, k_steps=K_STEPS,
                   reps=1) -> tuple[list[tuple[float, float]], dict]:
    """The engine row: same decode through the continuous-batching engine
    (no HTTP). Returns (``reps`` measurements of (tokens/sec, ttft_ms p50
    over the batch), per-phase host-time breakdown in cumulative ms) —
    callers take the median of the runs (r4 verdict: a single rep's
    variance on a loaded 1-core host swamps the quantity reported). The
    phase dict carries ``prefill_ms`` / ``transfer_ms`` / ``emit_ms``
    from EngineStats: where the serving path actually spends its host
    time, so a hot-path regression shows up as a phase, not a vibe."""
    eng = Engine(
        params,
        cfg,
        EngineConfig(max_batch_size=batch,
                     max_seq_len=cfg.max_seq_len, page_size=PAGE,
                     decode_steps_per_tick=k_steps,
                     # reps must never pay a prefill compile for a group
                     # shape an earlier rep's arrival split missed
                     warm_prefill_buckets=2),
    )
    eng.start()
    try:
        eng.warmup()
        # warm the prefill bucket for prompt_len AND both adaptive
        # decode-window programs at the serving page bucket (warmup()
        # compiles them at the idle bucket; the timed reps must not pay
        # the compile): enough tokens to ride the window ladder up
        done = threading.Event()
        eng.submit(GenRequest(
            prompt=[1] * prompt_len, max_tokens=3 * k_steps + 2,
            sampling=SamplingParams(temperature=0.0),
            emit=lambda t, f: done.set() if f else None,
        ))
        done.wait(timeout=600)

        out: list[tuple[float, float]] = []
        for rep in range(reps):
            dones = [threading.Event() for _ in range(batch)]
            counts = [0] * batch
            first_at = [0.0] * batch

            def mk(i):
                def emit(tok, fin):
                    if tok >= 0:
                        if counts[i] == 0:
                            first_at[i] = time.perf_counter()
                        counts[i] += 1
                    if fin is not None:
                        dones[i].set()
                return emit

            t0 = time.perf_counter()
            for i in range(batch):
                # distinct prompts per rep: the refcounted prefix cache
                # must not let rep N reuse rep N-1's prefill pages
                eng.submit(GenRequest(
                    prompt=[1 + i + rep * batch] * prompt_len,
                    max_tokens=gen_tokens,
                    sampling=SamplingParams(temperature=0.0), emit=mk(i),
                ))
            for d in dones:
                d.wait(timeout=600)
            dt = time.perf_counter() - t0
            ttfts = sorted((f - t0) * 1000.0 for f in first_at if f > 0)
            ttft_p50 = ttfts[len(ttfts) // 2] if ttfts else -1.0
            out.append((sum(counts) / dt, ttft_p50))
        phases = {
            "prefill_ms": round(eng.stats.prefill_ms, 1),
            "transfer_ms": round(eng.stats.transfer_ms, 1),
            "emit_ms": round(eng.stats.emit_ms, 1),
            "first_emit_ms": round(eng.stats.first_emit_ms, 1),
        }
        return out, phases
    finally:
        eng.stop()


# -- through-the-gateway leg (the north star's numerator) -----------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _start_tpuserve_subproc(model_name: str, cfg, quantize: str,
                            batch: int, k_steps: int,
                            engine: dict | None = None,
                            page: int = PAGE,
                            param_dtype: str = "",
                            lora: dict | None = None,
                            tp: int = 1,
                            sp: int = 1,
                            env_extra: dict | None = None,
                            family: str = "llama"):
    """Serve `model_name` over the real tpuserve HTTP surface in its own
    process (benchmarks/serve_child.py) — the deployment topology. The
    in-thread variant below shares the bench client's GIL, which on a
    1-core host turns the serve legs into a GIL-convoy measurement
    (spread 27-36% in r4/r5). Returns (base_url, stop_fn).

    CPU-leg only: the child env pins JAX_PLATFORMS=cpu, so wiring this
    into the live-TPU suite would silently serve from CPU while the
    raw/engine legs run on chip — the assert keeps that impossible."""
    assert jax.default_backend() == "cpu", \
        "subproc serve leg is pinned to the CPU backend"
    cfg_keys = ["vocab_size", "dim", "n_layers", "n_heads",
                "n_kv_heads", "ffn_dim", "max_seq_len", "rope_theta"]
    if family == "mixtral":
        # the --ab moe child (ISSUE 18) ships the expert geometry too
        cfg_keys += ["n_experts", "experts_per_token", "capacity_factor"]
    spec = {
        "model": model_name, "family": family,
        "cfg": {k: getattr(cfg, k) for k in cfg_keys},
        "batch": batch, "page": page, "k": k_steps, "quantize": quantize,
        "engine": engine or {}, "param_dtype": param_dtype,
        "lora": lora or {}, "tp": tp, "sp": sp,
    }
    here = os.path.dirname(os.path.abspath(__file__))
    proc = subprocess.Popen(
        [sys.executable, os.path.join(here, "benchmarks", "serve_child.py"),
         json.dumps(spec)],
        cwd=here, stdout=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu", **(env_extra or {})),
    )
    import select

    port = None
    deadline = time.time() + 1200
    buf = ""
    fd = proc.stdout.fileno()
    os.set_blocking(fd, False)
    while time.time() < deadline:
        # select-based read: a wedged-but-alive child must trip the
        # deadline, not block readline() forever while holding the lock
        if proc.poll() is not None:
            raise RuntimeError("tpuserve child exited before listening")
        r, _, _ = select.select([fd], [], [], 5.0)
        if not r:
            continue
        buf += os.read(fd, 4096).decode(errors="replace")
        *complete, buf = buf.split("\n")  # parse full lines only — a
        # read boundary can split SERVE_PORT=12345 into a valid-looking
        # truncated number
        for line in complete:
            if line.startswith("SERVE_PORT="):
                port = int(line.split("=", 1)[1])
                break
        if port is not None:
            break
    if port is None:
        proc.kill()
        raise RuntimeError("tpuserve child never reported a port")

    def stop():
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()

    return f"http://127.0.0.1:{port}", stop


def _start_tpuserve(model_name: str, cfg, quantize: str, batch: int,
                    k_steps: int = K_STEPS):
    """Serve `model_name` (registered on the fly, random weights) over
    the real tpuserve HTTP surface in a background thread. Returns
    (base_url, stop_fn)."""
    from aiohttp import web

    from aigw_tpu.models.registry import (
        ModelSpec,
        _REGISTRY,
        register_model,
    )
    from aigw_tpu.tpuserve.server import TPUServeServer

    if model_name not in _REGISTRY:
        register_model(ModelSpec(model_name, "llama", cfg))

    holder: dict = {}
    started = threading.Event()
    stopping = threading.Event()

    def run():
        async def main():
            server = TPUServeServer(
                model=model_name,
                engine_cfg=EngineConfig(
                    max_batch_size=batch, max_seq_len=cfg.max_seq_len,
                    page_size=PAGE, decode_steps_per_tick=k_steps,
                ),
                quantize=quantize,
            )
            runner = web.AppRunner(server.app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            holder["port"] = site._server.sockets[0].getsockname()[1]
            started.set()
            while not stopping.is_set():
                await asyncio.sleep(0.2)
            await runner.cleanup()

        asyncio.run(main())

    t = threading.Thread(target=run, daemon=True)
    t.start()
    if not started.wait(timeout=1200):
        raise RuntimeError("tpuserve failed to start within 20min")

    def stop():
        stopping.set()
        t.join(timeout=30)

    return f"http://127.0.0.1:{holder['port']}", stop


def _start_gateway(upstream_url: str):
    """`aigw run` (the real CLI) in a subprocess, routing everything to
    the tpuserve upstream. Forced onto the CPU JAX backend so it can
    never contend for the TPU the engine holds. Returns (url, proc,
    cfg_path)."""
    import tempfile

    import yaml

    cfg = {
        "version": "v1",
        "backends": [
            {"name": "tpuserve", "schema": "OpenAI", "url": upstream_url},
        ],
        "routes": [
            {"name": "bench", "rules": [{"backends": ["tpuserve"]}]},
        ],
    }
    f = tempfile.NamedTemporaryFile("w", suffix=".yaml", delete=False)
    yaml.safe_dump(cfg, f)
    f.close()
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "aigw_tpu", "run", f.name,
         "--port", str(port)],
        cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
    )
    return f"http://127.0.0.1:{port}", proc, f.name


async def _wait_health(url: str, timeout_s: float = 60.0) -> None:
    import aiohttp

    deadline = time.time() + timeout_s
    async with aiohttp.ClientSession() as s:
        while time.time() < deadline:
            try:
                async with s.get(url + "/health") as r:
                    if r.status == 200:
                        return
            except aiohttp.ClientError:
                pass
            await asyncio.sleep(0.3)
    raise RuntimeError(f"{url}/health never came up")


async def _drive_stream(url: str, model: str, batch: int, prompt_len: int,
                        gen_tokens: int, tag: str = "") -> tuple[float, float]:
    """batch concurrent streaming chats; returns (tokens/sec, ttft_ms_p50).
    TTFT = first content delta on the wire; tok/s = usage-reported
    completion tokens / wall clock for the whole batch. ``tag`` makes
    prompts unique per leg — the engine's refcounted prefix cache would
    otherwise let the second leg reuse the first leg's prefill pages and
    invert the direct-vs-gateway comparison."""
    import aiohttp

    ttfts: list[float] = []
    totals: list[int] = []

    async def one(s: aiohttp.ClientSession, i: int, t0: float) -> None:
        body = (tag + chr(65 + i % 26)) * prompt_len
        payload = {
            "model": model,
            "messages": [
                {"role": "user", "content": body[:prompt_len]}
            ],
            "max_tokens": gen_tokens,
            "temperature": 0.0,
            "stream": True,
            "stream_options": {"include_usage": True},
            # Pin every sampled token to a visible ASCII byte ('a'): with
            # random weights, greedy output is mostly UTF-8 continuation
            # bytes that the windowed StreamingDecoder emits as EMPTY
            # pieces — no SSE chunk on the wire — so "first content
            # delta" TTFT was measured over the lottery subset of
            # requests that happened to produce visible text (the r4
            # "988ms gateway TTFT penalty" was this artifact, not the
            # gateway). The bias rides the real sampling path (engine
            # bias_row), so the measured pipeline is unchanged.
            "logit_bias": {"97": 100},
        }
        first = None
        usage = None
        ntok = 0
        async with s.post(url + "/v1/chat/completions",
                          json=payload) as resp:
            body_preview = b""
            if resp.status != 200:
                body_preview = await resp.read()
            assert resp.status == 200, (resp.status, body_preview[:500])
            while True:
                line = await resp.content.readline()
                if not line:
                    break
                line = line.strip()
                if not line.startswith(b"data: "):
                    continue
                data = line[6:]
                if data == b"[DONE]":
                    break
                ev = json.loads(data)
                if ev.get("usage"):
                    usage = ev["usage"]
                ch = ev.get("choices") or []
                if ch and (ch[0].get("delta") or {}).get("content"):
                    if first is None:
                        first = (time.perf_counter() - t0) * 1000.0
                    ntok += 1
        if first is not None:
            ttfts.append(first)
        totals.append((usage or {}).get("completion_tokens") or ntok)

    timeout = aiohttp.ClientTimeout(total=1200)
    async with aiohttp.ClientSession(timeout=timeout) as s:
        t0 = time.perf_counter()
        await asyncio.gather(*(one(s, i, t0) for i in range(batch)))
        wall = time.perf_counter() - t0
    ttfts.sort()
    p50 = ttfts[len(ttfts) // 2] if ttfts else -1.0
    return sum(totals) / wall, p50


def _median(xs: list[float]) -> float:
    s = sorted(xs)
    return s[len(s) // 2] if s else 0.0


def _spread(xs: list[float]) -> float:
    """(max - min) / median — the r4 verdict's harness-stability gauge.
    With ≥5 reps the extremes are trimmed first: on a 1-core host a
    single background event (tunnel probe, log flush) poisons one rep,
    and the question is whether the *typical* reps agree."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    if len(xs) >= 5:
        xs = xs[1:-1]
    m = _median(xs)
    return (xs[-1] - xs[0]) / m if m else 0.0


def gateway_numbers(model_name: str, cfg, quantize: str, batch=BATCH,
                    prompt_len=PROMPT_LEN, gen_tokens=GEN_TOKENS,
                    k_steps=K_STEPS, reps=3, subproc=False) -> dict:
    """The north-star numerator: tokens/sec and TTFT through
    `aigw run` → tpuserve → engine over streaming /v1/chat/completions,
    plus the same load sent directly to tpuserve (isolates gateway
    overhead from HTTP-serving overhead). ``reps`` interleaved
    direct/gateway trials; medians + spread (r4 verdict: best-of-2 on a
    loaded host reported noise as signal). ``subproc`` runs tpuserve as
    its own process (the deployment topology; used by the CPU leg where
    GIL sharing corrupted the measurement)."""
    start = _start_tpuserve_subproc if subproc else _start_tpuserve
    serve_url, stop_serve = start(model_name, cfg, quantize,
                                  batch, k_steps)
    gw_url, proc, cfg_path = _start_gateway(serve_url)

    async def run() -> dict:
        await _wait_health(serve_url, 1200)
        await _wait_health(gw_url, 120)
        # warm every prefill bucket + gateway code path off the clock —
        # long enough to compile BOTH adaptive decode-window programs at
        # the serving page bucket (kmin fires young, K after steady)
        warm_gen = max(4, 3 * k_steps + 2)
        await _drive_stream(serve_url, model_name, batch, prompt_len,
                            warm_gen, tag="w")
        await _drive_stream(gw_url, model_name, batch, prompt_len,
                            warm_gen, tag="x")
        # interleave the legs so slow drift (CPU clocks, cache warmth)
        # cancels instead of flattering whichever leg runs later
        d_tps, d_ttft, g_tps, g_ttft = [], [], [], []
        for trial in range(reps):
            dt, dt_ttft = await _drive_stream(
                serve_url, model_name, batch, prompt_len, gen_tokens,
                tag=f"d{trial}")
            gt, gt_ttft = await _drive_stream(
                gw_url, model_name, batch, prompt_len, gen_tokens,
                tag=f"g{trial}")
            d_tps.append(dt)
            d_ttft.append(dt_ttft)
            g_tps.append(gt)
            g_ttft.append(gt_ttft)
        # server-side phase percentiles straight from the replica's
        # histograms (/state phase_percentiles, ISSUE 5) — p50/p95/p99
        # for TTFT and per-token latency come from the serving path's
        # own distributions, not recomputed from the client's samples
        phase_pct: dict = {}
        warm_fields: dict = {}
        try:
            async with aiohttp.ClientSession() as s:
                async with s.get(serve_url + "/state") as r:
                    st = await r.json()
                    phase_pct = st.get("phase_percentiles", {})
                    # warmup cost of the serve replica (ISSUE 6): the
                    # "collapsed compile surface = faster cold start"
                    # claim is measured, not asserted
                    warm_fields = {
                        "serve_warmup_ms": st.get("warmup_ms", 0.0),
                        "serve_warm_programs": st.get(
                            "warm_programs", 0),
                        "serve_attention_backend": st.get(
                            "attention_backend", ""),
                    }
        except aiohttp.ClientError:
            pass
        return {
            "gateway_tps": _median(g_tps),
            "gateway_ttft_ms_p50": _median(g_ttft),
            "direct_tps": _median(d_tps),
            "direct_ttft_ms_p50": _median(d_ttft),
            "gateway_tps_spread": round(_spread(g_tps), 3),
            "direct_tps_spread": round(_spread(d_tps), 3),
            "serve_phase_percentiles": phase_pct,
            **warm_fields,
        }

    try:
        return asyncio.run(run())
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        os.unlink(cfg_path)
        stop_serve()


# -- gateway_prefix leg: prefix-cache cold vs warm TTFT (ISSUE 3) --------

#: ByteTokenizer chat template: "<system>: {sys}\n<user>: " is the
#: token head every request shares — 19 chars of scaffolding + the
#: system prompt. 45 system chars → a 64-token shared prefix, page-
#: aligned at the leg's 16-token pages (4 reusable pages per request).
_PREFIX_SYS = "You are a terse assistant. Reply briefly, no".ljust(45, ".")
_PREFIX_PAGE = 16
_PREFIX_MIN_BUCKET = 32
# Leg model: a notch bigger than CPU_CFG so per-request device compute
# dominates the serving stack's fixed per-request cost (HTTP, probe,
# emit) — the quantity under test is prefill width, not overhead.
_PREFIX_CFG = llama.LlamaConfig(
    vocab_size=8192, dim=768, n_layers=6, n_heads=8, n_kv_heads=4,
    ffn_dim=2048, max_seq_len=512, rope_theta=10000.0,
)


async def _drive_prefix_one(s, url: str, model: str, user: str,
                            gen_tokens: int) -> float:
    """One sequential streaming chat; returns TTFT ms (first content
    delta on the wire — the logit-bias visible-token rig from
    _drive_stream)."""
    payload = {
        "model": model,
        "messages": [
            {"role": "system", "content": _PREFIX_SYS},
            {"role": "user", "content": user},
        ],
        "max_tokens": gen_tokens,
        "temperature": 0.0,
        "stream": True,
        "logit_bias": {"97": 100},
    }
    t0 = time.perf_counter()
    first = -1.0
    async with s.post(url + "/v1/chat/completions", json=payload) as resp:
        assert resp.status == 200, resp.status
        while True:
            line = await resp.content.readline()
            if not line:
                break
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            data = line[6:]
            if data == b"[DONE]":
                break
            ev = json.loads(data)
            ch = ev.get("choices") or []
            if ch and (ch[0].get("delta") or {}).get("content"):
                if first < 0:
                    first = (time.perf_counter() - t0) * 1000.0
    return first


async def _get_state(s, url: str) -> dict:
    async with s.get(url + "/state") as resp:
        return await resp.json()


def prefix_cache_numbers(reps: int = 3, requests_per_rep: int = 6,
                         gen_tokens: int = 8) -> dict:
    """The ``gateway_prefix`` leg: chat requests sharing a 64-token
    system-prompt head (~96-token prompts, 18-char unique user tails)
    against TWO tpuserve replicas — prefix cache ON (warm: every
    request resumes prefill at the shared 64-token offset) and OFF
    (cold: full-prompt prefill every time). Reps INTERLEAVE the two
    servers (the ``--ab prefix_cache`` capture mode), so the ±15% host
    drift documented for this box cancels out of the warm/cold ratio.
    Sequential requests: the quantity under test is one request's
    prefill, not batch scheduling. Reports TTFT p50 and per-request
    device prefill_ms for both sides plus the warm replica's
    prefix_cache_hit_rate."""
    import aiohttp

    model_name = "bench-prefix-tiny"
    # num_pages sized to the leg (4 slots × ~7 pages + cached prefix +
    # headroom), NOT the auto max_batch×max_seq default: XLA:CPU's K/V
    # scatter walks the whole cache buffer, so an oversized pool buries
    # the padded-width signal under a fixed per-call cost on this host
    # f32 weights + KV on the CPU leg: XLA:CPU repacks bf16 weight
    # arguments to f32 EVERY call — a width-independent ~35ms tax that
    # buries the padded-width signal under test (bf16 is native on TPU)
    engine_common = {"min_prefill_bucket": _PREFIX_MIN_BUCKET,
                     "num_pages": 48, "max_queued_requests": 64,
                     "kv_cache_dtype": "float32"}
    url_on, stop_on = _start_tpuserve_subproc(
        model_name, _PREFIX_CFG, "", batch=4,
        k_steps=int(os.environ.get("AIGW_BENCH_CPU_K", "4")),
        engine=dict(engine_common, enable_prefix_cache=True),
        page=_PREFIX_PAGE, param_dtype="float32")
    url_off, stop_off = _start_tpuserve_subproc(
        model_name, _PREFIX_CFG, "", batch=4,
        k_steps=int(os.environ.get("AIGW_BENCH_CPU_K", "4")),
        engine=dict(engine_common, enable_prefix_cache=False),
        page=_PREFIX_PAGE, param_dtype="float32")

    async def run() -> dict:
        await _wait_health(url_on, 1200)
        await _wait_health(url_off, 1200)
        timeout = aiohttp.ClientTimeout(total=1200)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            # off-the-clock warm pass: compiles every shape BOTH legs
            # dispatch (96-wide cold prefill, 32-wide suffix resume,
            # both decode-window programs) and primes the shared
            # prefix pages on the cache-on replica
            for url in (url_on, url_off):
                for i in range(3):
                    await _drive_prefix_one(
                        s, url, model_name, f"warmup tail {i:02d}..",
                        gen_tokens)
            warm_t, cold_t = [], []
            st_on0 = await _get_state(s, url_on)
            st_off0 = await _get_state(s, url_off)
            n = 0
            for rep in range(reps):
                # interleave A/B: cache-on then cache-off within each
                # rep so slow host drift cancels from the ratio
                for i in range(requests_per_rep):
                    user = f"q{rep}{i:02d} tail of chat..."[:18]
                    warm_t.append(await _drive_prefix_one(
                        s, url_on, model_name, user, gen_tokens))
                    cold_t.append(await _drive_prefix_one(
                        s, url_off, model_name, user, gen_tokens))
                    n += 1
            st_on1 = await _get_state(s, url_on)
            st_off1 = await _get_state(s, url_off)
        warm = _median([t for t in warm_t if t > 0])
        cold = _median([t for t in cold_t if t > 0])
        return {
            "prefix_warm_ttft_ms_p50": round(warm, 1),
            "prefix_cold_ttft_ms_p50": round(cold, 1),
            "prefix_warm_vs_cold": round(warm / cold, 4) if cold else 0.0,
            "prefix_warm_prefill_ms": round(
                (st_on1["prefill_ms"] - st_on0["prefill_ms"]) / n, 1),
            "prefix_cold_prefill_ms": round(
                (st_off1["prefill_ms"] - st_off0["prefill_ms"]) / n, 1),
            "prefix_cache_hit_rate": st_on1.get(
                "prefix_cache_hit_rate", 0.0),
            "prefix_warm_ttft_spread": round(_spread(warm_t), 3),
            "prefix_cold_ttft_spread": round(_spread(cold_t), 3),
            "prefix_ab_reps": reps * requests_per_rep,
        }

    try:
        return asyncio.run(run())
    finally:
        stop_on()
        stop_off()


# -- spec_decode leg: speculative decoding on/off A/B (ISSUE 4) ----------

#: the speculative children's max draft rung (ladder {0, 2, 4})
_SPEC_TOKENS = 4
_SPEC_PAGE = 16
# Leg model: the ~200MB-of-f32-weights prefix-leg config, NOT the tiny
# ratio model. Speculation pays when a decode step is dominated by
# streaming weights (the TPU regime, and on this host the regime any
# model bigger than L3 cache is in): a (D+1)-wide verify then costs
# about one step. The 0.02B ratio model fits in cache — compute-bound,
# a 5-wide verify costs ~5 steps, and the measured "speedup" would be
# an artifact of the wrong regime in both directions.


def _spec_ab_fields(st0: dict, st1: dict) -> dict:
    """Acceptance telemetry of the spec-on child over one capture,
    derived from /state deltas (pure — unit-tested by the bench
    smoke). ``accepted_per_step`` is emitted tokens per device decode
    step: plain decode is ≤ 1.0 by construction, accepted drafts push
    it above."""
    drafted = st1.get("spec_drafted", 0) - st0.get("spec_drafted", 0)
    accepted = st1.get("spec_accepted", 0) - st0.get("spec_accepted", 0)
    steps = st1.get("decode_steps", 0) - st0.get("decode_steps", 0)
    toks = (st1.get("tokens_generated", 0)
            - st0.get("tokens_generated", 0))
    return {
        "spec_accept_rate": (round(accepted / drafted, 4)
                             if drafted > 0 else 0.0),
        "drafted_tokens": drafted,
        "accepted_per_step": round(toks / steps, 3) if steps > 0 else 0.0,
        "spec_state_rebuilds": st1.get("state_rebuilds", 0),
    }


async def _drive_spec_one(s, url: str, model: str, content: str,
                          gen_tokens: int, bias: bool) -> tuple:
    """One sequential streaming chat; returns (duration_s, tokens).
    ``bias`` pins every sampled token to 'a' — the repetitive-decode
    workload where drafts fully accept; without it the model free-runs
    and proposed drafts reject (the forced low-acceptance workload)."""
    payload = {
        "model": model,
        "messages": [{"role": "user", "content": content}],
        "max_tokens": gen_tokens,
        "temperature": 0.0,
        "stream": True,
        "stream_options": {"include_usage": True},
    }
    if bias:
        payload["logit_bias"] = {"97": 100}
    t0 = time.perf_counter()
    usage = None
    ntok = 0
    async with s.post(url + "/v1/chat/completions", json=payload) as resp:
        assert resp.status == 200, resp.status
        while True:
            line = await resp.content.readline()
            if not line:
                break
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            data = line[6:]
            if data == b"[DONE]":
                break
            ev = json.loads(data)
            if ev.get("usage"):
                usage = ev["usage"]
            ch = ev.get("choices") or []
            if ch and (ch[0].get("delta") or {}).get("content"):
                ntok += 1
    dur = time.perf_counter() - t0
    return dur, (usage or {}).get("completion_tokens") or ntok


def spec_decode_numbers(reps: int = 3, requests_per_rep: int = 4,
                        gen_tokens: int = 96) -> dict:
    """The ``spec_decode`` A/B leg: decode-heavy sequential streaming
    chats against THREE tpuserve children — spec-on for the repetitive
    workload, spec-on for the low-acceptance workload, and spec-off
    (serving both workloads as the control). Requests INTERLEAVE
    on/off within each rep (the prefix_cache capture pattern), so host
    drift cancels out of the tok/s ratios.

    Two spec-on children, not one: the engine-wide acceptance prior is
    traffic-dependent by design — mixing workloads through one child
    would measure the prior thrashing between regimes instead of each
    regime's steady state. The three criteria this leg reports against:
    accepted_per_step > 1.3 and spec-on/spec-off tok/s ≥ 1.15 on the
    repetitive leg; spec-on within 3% of spec-off on the forced
    low-acceptance leg (the adaptive ladder collapsed to D=0)."""
    import aiohttp

    model_name = "bench-spec-tiny"
    engine_common = {"min_prefill_bucket": 32, "num_pages": 64,
                     "max_queued_requests": 64,
                     "kv_cache_dtype": "float32"}
    k = int(os.environ.get("AIGW_BENCH_CPU_K", "4"))
    children = []

    def start(spec: int):
        url, stop = _start_tpuserve_subproc(
            model_name, _PREFIX_CFG, "", batch=4, k_steps=k,
            engine=dict(engine_common, spec_tokens=spec),
            page=_SPEC_PAGE, param_dtype="float32")
        children.append(stop)
        return url

    url_rep = start(_SPEC_TOKENS)   # spec-on, repetitive workload
    url_low = start(_SPEC_TOKENS)   # spec-on, low-acceptance workload
    url_off = start(0)              # control

    # repetitive: 'ababab…' prompt + bias→'a' output = the n-gram
    # source's best case. low-acceptance: the prompt's repeated tail
    # bigram FORCES proposals, the free-running random-weight greedy
    # stream rejects them (no proposals at all would never exercise
    # the ladder).
    rep_content = "ab" * 16
    low_content = "the quick brown fox xq jumps over wp lazy dogs xq"

    async def run() -> dict:
        await _wait_health(url_rep, 1200)
        await _wait_health(url_low, 1200)
        await _wait_health(url_off, 1200)
        timeout = aiohttp.ClientTimeout(total=1200)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            # off the clock: compile every dispatched program (plain
            # lean/full, every draft rung the collapse path crosses)
            # and teach each spec child its workload's acceptance
            # prior — the low-acceptance criterion is about the
            # ladder's steady state, not its first-contact cost
            for url, content, b in ((url_rep, rep_content, True),
                                    (url_low, low_content, False),
                                    (url_off, rep_content, True),
                                    (url_off, low_content, False)):
                for _ in range(5):
                    await _drive_spec_one(s, url, model_name, content,
                                          gen_tokens, b)
            st_rep0 = await _get_state(s, url_rep)
            st_low0 = await _get_state(s, url_low)
            on_rep, off_rep, on_low, off_low = [], [], [], []
            for _rep in range(reps):
                for _i in range(requests_per_rep):
                    on_rep.append(await _drive_spec_one(
                        s, url_rep, model_name, rep_content,
                        gen_tokens, True))
                    off_rep.append(await _drive_spec_one(
                        s, url_off, model_name, rep_content,
                        gen_tokens, True))
                    on_low.append(await _drive_spec_one(
                        s, url_low, model_name, low_content,
                        gen_tokens, False))
                    off_low.append(await _drive_spec_one(
                        s, url_off, model_name, low_content,
                        gen_tokens, False))
            st_rep1 = await _get_state(s, url_rep)
            st_low1 = await _get_state(s, url_low)

        def tps(runs):
            return sum(n for _, n in runs) / sum(d for d, _ in runs)

        fields = _spec_ab_fields(st_rep0, st_rep1)
        low = _spec_ab_fields(st_low0, st_low1)
        on, off = tps(on_rep), tps(off_rep)
        lon, loff = tps(on_low), tps(off_low)
        return {
            "spec_on_tps": round(on, 1),
            "spec_off_tps": round(off, 1),
            "spec_speedup": round(on / off, 4) if off else 0.0,
            "spec_low_on_tps": round(lon, 1),
            "spec_low_off_tps": round(loff, 1),
            "spec_low_overhead": (round(1.0 - lon / loff, 4)
                                  if loff else 0.0),
            "spec_low_draft_len": st_low1.get("spec_draft_len", -1),
            "spec_low_accept_rate": low["spec_accept_rate"],
            "spec_ab_reps": reps * requests_per_rep,
            **fields,
        }

    try:
        return asyncio.run(run())
    finally:
        for stop in children:
            stop()


# -- ragged_prefill leg: attention-backend A/B (ISSUE 6) -----------------

# Leg model: tiny llama with a 2048 sequence budget so the mixed-length
# burst can carry a real long prompt. Page 64 keeps the ragged XLA
# fallback's per-page window loop short on the CPU host.
_RAGGED_CFG = llama.LlamaConfig(
    vocab_size=2048, dim=256, n_layers=4, n_heads=8, n_kv_heads=4,
    ffn_dim=512, max_seq_len=2048, rope_theta=10000.0,
)
_RAGGED_PAGE = 64
#: the burst's prompt lengths in TOKENS (byte tokenizer: chars + bos).
#: Five ~97-token chat-sized prompts — on the bucket ladder they share
#: the 128 bucket, so the batched group pads 5 rows to 8 — plus one
#: 1024-token prompt. Total 1509 tokens: the ragged pack runs ONE
#: 1536-wide program (chunk-residue padding only).
_RAGGED_MIX = (97, 97, 97, 97, 97, 1024)


async def _drive_ragged_burst(s, url: str, model: str,
                              gen_tokens: int, tag: str) -> list[float]:
    """Fire the mixed-length burst CONCURRENTLY (one coalesced
    admission) as /v1/completions streams; returns per-request TTFT ms
    (first content delta on the wire)."""

    async def one(n_tokens: int, i: int) -> float:
        text = (f"{tag}{i:02d}" + "x" * n_tokens)[: n_tokens - 1]
        payload = {
            "model": model,
            "prompt": text,
            "max_tokens": gen_tokens,
            "temperature": 0.0,
            "stream": True,
            "logit_bias": {"97": 100},
        }
        t0 = time.perf_counter()
        first = -1.0
        async with s.post(url + "/v1/completions", json=payload) as resp:
            assert resp.status == 200, resp.status
            while True:
                line = await resp.content.readline()
                if not line:
                    break
                line = line.strip()
                if not line.startswith(b"data: "):
                    continue
                data = line[6:]
                if data == b"[DONE]":
                    break
                ev = json.loads(data)
                ch = ev.get("choices") or []
                if ch and ch[0].get("text"):
                    if first < 0:
                        first = (time.perf_counter() - t0) * 1000.0
        return first

    return list(await asyncio.gather(
        *(one(n, i) for i, n in enumerate(_RAGGED_MIX))))


def _ragged_ab_fields(st0: dict, st1: dict, prefix: str) -> dict:
    """One child's padding-tax + compile telemetry over a capture,
    derived from /state deltas (pure — unit-tested by the bench
    smoke)."""
    real = (st1.get("prefill_tokens_real", 0)
            - st0.get("prefill_tokens_real", 0))
    padded = (st1.get("prefill_tokens_padded", 0)
              - st0.get("prefill_tokens_padded", 0))
    return {
        f"{prefix}_padded_frac": (round(1.0 - real / padded, 4)
                                  if padded > 0 else 0.0),
        f"{prefix}_prefill_tokens": real,
        f"{prefix}_warm_programs": st1.get("warm_programs", 0),
        f"{prefix}_warmup_ms": st1.get("warmup_ms", 0.0),
        f"{prefix}_hot_compiles": (st1.get("xla_compiles", 0)
                                   - st0.get("xla_compiles", 0)),
    }


def ragged_prefill_numbers(reps: int = 3, gen_tokens: int = 8) -> dict:
    """The ``ragged_prefill`` A/B leg: the same mixed-length admission
    burst (five ~97-token prompts + one 1024-token prompt, fired
    concurrently so the engine coalesces them) against TWO tpuserve
    children — attention backend pallas-ragged vs xla-bucketed — with
    reps interleaved so host drift cancels. What it measures:

    - ``padded_frac`` per backend from the /state token counters: the
      bucketed ladder pays per-sequence bucket padding PLUS the
      batched group's pow2 row padding (5 same-bucket prompts pad to
      8 rows); the ragged pack pays only the token-budget chunk
      residue of the burst total.
    - warm-path compile surface: ``warm_programs`` after warmup (the
      ragged rung ladder vs every (bucket, group) shape), ``warmup_ms``
      cold-start cost, and zero hot compiles over the timed reps.
    - TTFT medians for reference. NOTE: on this CPU host the ragged
      child runs the XLA windowed fallback, whose page loop walks the
      full 2048-token window — absolute TTFT is NOT the claim here
      (the DMA-skip kernel only exists on TPU); padded compute and
      compile surface are."""
    import aiohttp

    model_name = "bench-ragged-tiny"
    engine_common = {
        "min_prefill_bucket": 32, "num_pages": 56,
        "max_queued_requests": 64, "kv_cache_dtype": "float32",
        "enable_prefix_cache": False,
        # the quantity under test is one coalesced burst's geometry —
        # give the 6 concurrent submits a wider idle-coalesce window so
        # event-loop scheduling jitter can't split the burst (both
        # children identical; the wait cancels from the A/B)
        "admission_coalesce_ms": 20.0,
    }
    url_rag, stop_rag = _start_tpuserve_subproc(
        model_name, _RAGGED_CFG, "", batch=8,
        k_steps=int(os.environ.get("AIGW_BENCH_CPU_K", "4")),
        engine=dict(engine_common, attention_backend="pallas-ragged"),
        page=_RAGGED_PAGE, param_dtype="float32")
    url_bkt, stop_bkt = _start_tpuserve_subproc(
        model_name, _RAGGED_CFG, "", batch=8,
        k_steps=int(os.environ.get("AIGW_BENCH_CPU_K", "4")),
        engine=dict(engine_common, attention_backend="xla-bucketed"),
        page=_RAGGED_PAGE, param_dtype="float32")

    async def run() -> dict:
        await _wait_health(url_rag, 1200)
        await _wait_health(url_bkt, 1200)
        timeout = aiohttp.ClientTimeout(total=1200)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            # off-the-clock warm pass: compiles every shape either leg
            # dispatches beyond the warmed ladders (decode page-bucket
            # growth for the 1024-token stream, singleton group shapes)
            for url in (url_rag, url_bkt):
                await _drive_ragged_burst(s, url, model_name,
                                          gen_tokens, "w")
            st_rag0 = await _get_state(s, url_rag)
            st_bkt0 = await _get_state(s, url_bkt)
            rag_t, bkt_t = [], []
            for rep in range(reps):
                rag_t.extend(await _drive_ragged_burst(
                    s, url_rag, model_name, gen_tokens, f"r{rep}"))
                bkt_t.extend(await _drive_ragged_burst(
                    s, url_bkt, model_name, gen_tokens, f"b{rep}"))
            st_rag1 = await _get_state(s, url_rag)
            st_bkt1 = await _get_state(s, url_bkt)
        rag = _median([t for t in rag_t if t > 0])
        bkt = _median([t for t in bkt_t if t > 0])
        return {
            "ragged_ttft_ms_p50": round(rag, 1),
            "bucketed_ttft_ms_p50": round(bkt, 1),
            "ragged_vs_bucketed_ttft": (round(rag / bkt, 4)
                                        if bkt else 0.0),
            "ragged_backend": st_rag1.get("attention_backend", ""),
            "ragged_ttft_spread": round(_spread(rag_t), 3),
            "bucketed_ttft_spread": round(_spread(bkt_t), 3),
            "ragged_ab_reps": reps * len(_RAGGED_MIX),
            **_ragged_ab_fields(st_rag0, st_rag1, "ragged"),
            **_ragged_ab_fields(st_bkt0, st_bkt1, "bucketed"),
        }

    try:
        return asyncio.run(run())
    finally:
        stop_rag()
        stop_bkt()


# -- mesh leg: tensor-parallel serving A/B (ISSUE 10) ---------------------

#: tensor-parallel degree of the mesh child (virtual devices via
#: XLA_FLAGS on the child env — the flag must precede jax init, which
#: is why this leg NEEDS the subprocess topology)
_MESH_TP = 8
#: n_kv_heads divisible by _MESH_TP so the paged KV pool shards on
#: heads (one KV head per virtual device at tp=8)
_MESH_CFG = llama.LlamaConfig(
    vocab_size=2048, dim=256, n_layers=4, n_heads=8, n_kv_heads=8,
    ffn_dim=512, max_seq_len=512, rope_theta=10000.0,
)
_MESH_PAGE = 32
#: the timed burst: mixed prompt lengths in tokens (byte tokenizer),
#: fired concurrently so both children coalesce one admission
_MESH_MIX = (24, 48, 90, 90, 130, 200)


def _mesh_ab_fields(st0: dict, st1: dict, prefix: str) -> dict:
    """One child's mesh telemetry over a capture, derived from /state
    deltas (pure — unit-tested by the bench smoke). The parameter-split
    fraction is worst-device bytes × devices ÷ total: 1.0 = a perfect
    total/tp split, the bench's ±10% memory claim."""
    total = int(st1.get("param_bytes_total", 0) or 0)
    per = st1.get("param_bytes_per_device") or {}
    n = max(1, len(per))
    worst = max((int(v) for v in per.values()), default=0)
    return {
        f"{prefix}_devices": int(st1.get("mesh_devices", 1) or 1),
        f"{prefix}_param_bytes_total": total,
        f"{prefix}_param_bytes_per_device_max": worst,
        f"{prefix}_param_split_frac": (round(worst * n / total, 4)
                                       if total else 0.0),
        f"{prefix}_hot_compiles": (st1.get("xla_compiles", 0)
                                   - st0.get("xla_compiles", 0)),
        f"{prefix}_ici_bytes_per_token": int(
            st1.get("ici_bytes_per_token", 0) or 0),
    }


async def _drive_mesh_burst(s, url: str, model: str, gen_tokens: int,
                            tag: str) -> tuple[list[str], float]:
    """Fire the mixed burst concurrently as streaming /v1/completions;
    returns (per-request full texts in submit order, wall seconds).
    One slot samples (explicit seed — deterministic across children),
    one carries a repetition penalty, the rest run greedy: the mixed-
    feature batch whose streams must be byte-identical mesh vs single."""

    async def one(n_tokens: int, i: int) -> str:
        text = (f"{tag}{i:02d}" + "x" * n_tokens)[: n_tokens - 1]
        payload = {
            "model": model, "prompt": text, "max_tokens": gen_tokens,
            "temperature": 0.0, "stream": True,
        }
        if i == 1:
            payload.update(temperature=0.8, top_p=0.9, seed=1234 + i)
        elif i == 2:
            payload["frequency_penalty"] = 0.6
        out: list[str] = []
        async with s.post(url + "/v1/completions", json=payload) as resp:
            assert resp.status == 200, resp.status
            while True:
                line = await resp.content.readline()
                if not line:
                    break
                line = line.strip()
                if not line.startswith(b"data: "):
                    continue
                data = line[6:]
                if data == b"[DONE]":
                    break
                ev = json.loads(data)
                ch = ev.get("choices") or []
                if ch and ch[0].get("text"):
                    out.append(ch[0]["text"])
        return "".join(out)

    t0 = time.perf_counter()
    texts = list(await asyncio.gather(
        *(one(n, i) for i, n in enumerate(_MESH_MIX))))
    return texts, time.perf_counter() - t0


def mesh_numbers(reps: int = 3, gen_tokens: int = 24) -> dict:
    """The ``mesh`` A/B leg (ISSUE 10): the SAME seeded mixed-feature
    traffic against TWO tpuserve children — tp=8 over 8 virtual CPU
    devices (XLA_FLAGS on the child env) vs single-device — f32 params
    and KV so greedy streams are deterministic. The portable claims:

    - **byte-identity**: every stream matches between the children
      (the sharded engine is the same engine);
    - **memory split**: per-device parameter bytes ≈ total/tp (±10%),
      measured from real shard layouts on /state;
    - **compile surface**: zero hot XLA compiles on the warmed mesh
      path over the timed reps.

    ``mesh_vs_single`` throughput is reported with spreads but is
    INFORMATIONAL on CPU: 8 virtual devices time-slice one host core,
    so the ratio measures partitioning overhead, not ICI speedup."""
    import aiohttp

    model_name = "bench-mesh-tiny"
    engine_common = {
        "min_prefill_bucket": 32, "kv_cache_dtype": "float32",
        "max_queued_requests": 64, "admission_coalesce_ms": 20.0,
        # decode programs re-trace per page bucket: warm the rungs the
        # mixed burst reaches (≤ 8 pages) so the timed reps stay
        # compile-free on BOTH children
        "warm_decode_buckets": 4,
    }
    k = int(os.environ.get("AIGW_BENCH_CPU_K", "4"))
    url_mesh, stop_mesh = _start_tpuserve_subproc(
        model_name, _MESH_CFG, "", batch=8, k_steps=k,
        engine=dict(engine_common), page=_MESH_PAGE,
        param_dtype="float32", tp=_MESH_TP,
        env_extra={"XLA_FLAGS":
                   f"--xla_force_host_platform_device_count={_MESH_TP}"})
    url_one, stop_one = _start_tpuserve_subproc(
        model_name, _MESH_CFG, "", batch=8, k_steps=k,
        engine=dict(engine_common), page=_MESH_PAGE,
        param_dtype="float32")

    async def run() -> dict:
        await _wait_health(url_mesh, 1200)
        await _wait_health(url_one, 1200)
        timeout = aiohttp.ClientTimeout(total=1200)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            # off-the-clock warm pass (page-bucket growth, singleton
            # shapes the warmed ladder doesn't cover)
            for url in (url_mesh, url_one):
                await _drive_mesh_burst(s, url, model_name, gen_tokens,
                                        "w")
            st_mesh0 = await _get_state(s, url_mesh)
            st_one0 = await _get_state(s, url_one)
            identical = True
            mesh_tps, one_tps = [], []
            for rep in range(reps):
                m_texts, m_wall = await _drive_mesh_burst(
                    s, url_mesh, model_name, gen_tokens, f"r{rep}")
                o_texts, o_wall = await _drive_mesh_burst(
                    s, url_one, model_name, gen_tokens, f"r{rep}")
                identical &= m_texts == o_texts
                n_tok = gen_tokens * len(_MESH_MIX)
                mesh_tps.append(n_tok / m_wall)
                one_tps.append(n_tok / o_wall)
            st_mesh1 = await _get_state(s, url_mesh)
            st_one1 = await _get_state(s, url_one)
        m, o = _median(mesh_tps), _median(one_tps)
        return {
            "mesh_tp": _MESH_TP,
            "mesh_byte_identical": identical,
            "mesh_tokens_per_sec": round(m, 2),
            "single_tokens_per_sec": round(o, 2),
            "mesh_vs_single": round(m / o, 4) if o else 0.0,
            "mesh_tps_spread": round(_spread(mesh_tps), 3),
            "single_tps_spread": round(_spread(one_tps), 3),
            "mesh_axes": {a: n for a, n in (
                st_mesh1.get("mesh_axes") or {}).items() if n > 1},
            "mesh_ab_reps": reps * len(_MESH_MIX),
            **_mesh_ab_fields(st_mesh0, st_mesh1, "mesh"),
            **_mesh_ab_fields(st_one0, st_one1, "single"),
        }

    try:
        return asyncio.run(run())
    finally:
        stop_mesh()
        stop_one()


# -- lora leg: multi-LoRA adapter serving A/B (ISSUE 7) -------------------

#: adapters in the child's zoo / device rows for them. rows < zoo so the
#: churn phase exercises a real evict+reload; the TIMED mix rotates only
#: the first `_LORA_ROWS` adapters (all resident after the warm pass) —
#: the parity claim is about the zero-row batch, not LRU thrash.
_LORA_ZOO = 5
_LORA_ROWS = 4


def _lora_ab_fields(st0: dict, st1: dict) -> dict:
    """Adapter-subsystem telemetry over a capture, derived from /state
    deltas (pure — unit-tested by the bench smoke)."""
    return {
        "adapter_loads": (st1.get("adapter_loads", 0)
                          - st0.get("adapter_loads", 0)),
        "adapter_evictions": (st1.get("adapter_evictions", 0)
                              - st0.get("adapter_evictions", 0)),
        "adapters_resident": len(st1.get("adapters_resident") or ()),
        "lora_hot_compiles": (st1.get("xla_compiles", 0)
                              - st0.get("xla_compiles", 0)),
    }


def lora_numbers(reps: int = 3, requests_per_rep: int = 4,
                 gen_tokens: int = 64) -> dict:
    """The ``lora`` A/B leg: ONE tpuserve child serving a 5-adapter zoo
    over 4 device rows; decode-heavy sequential streaming chats
    interleave adapter-mix traffic (model ``<base>:t{i}``, rotating
    adapters so the batch's adapter_idx mix changes every request)
    with base-only traffic (the zero-row control) — host drift cancels
    from the tok/s ratio. The criteria this leg reports against:

    - ``lora_mix_vs_base`` ≥ 0.95: an adapter-mix request stream is
      within 5% tok/s of base-only serving on the SAME engine (one
      compiled program serves any mix; the zero row is an adapter row,
      so the control pays the identical gather).
    - ``lora_hot_compiles`` == 0 over the timed reps AND the churn
      phase (hot load of a non-resident adapter + evict/reload swap a
      row's CONTENT, never its program).
    - ``adapter_loads``/``adapter_evictions`` > 0 in the churn phase:
      the subsystem actually cycled rows, it didn't just serve a
      static stack."""
    import aiohttp

    model_name = "bench-lora-tiny"
    k = int(os.environ.get("AIGW_BENCH_CPU_K", "4"))
    url, stop = _start_tpuserve_subproc(
        model_name, _PREFIX_CFG, "", batch=4, k_steps=k,
        engine={"min_prefill_bucket": 32, "num_pages": 64,
                "max_queued_requests": 64, "kv_cache_dtype": "float32"},
        page=_SPEC_PAGE, param_dtype="float32",
        lora={"adapters": _LORA_ZOO, "rank": 8, "slots": _LORA_ROWS})
    content = "ab" * 16

    async def run() -> dict:
        await _wait_health(url, 1200)
        timeout = aiohttp.ClientTimeout(total=1200)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            # off the clock: hot-load the timed rotation's adapters and
            # compile every dispatched shape (decode page bucket, the
            # prefill rung, the adapter-load row scatters ride warmup)
            for i in range(_LORA_ROWS):
                await _drive_spec_one(s, url, f"{model_name}:t{i}",
                                      content, gen_tokens, True)
            await _drive_spec_one(s, url, model_name, content,
                                  gen_tokens, True)
            st0 = await _get_state(s, url)
            mix, base = [], []
            for _rep in range(reps):
                for i in range(requests_per_rep):
                    mix.append(await _drive_spec_one(
                        s, url, f"{model_name}:t{i % _LORA_ROWS}",
                        content, gen_tokens, True))
                    base.append(await _drive_spec_one(
                        s, url, model_name, content, gen_tokens, True))
            st1 = await _get_state(s, url)
            # churn phase (adapter-mix change): t4 is NOT resident —
            # admitting it hot-loads over the LRU row; re-asking the
            # evicted adapter reloads it. Still zero compiles.
            for m in (f"{model_name}:t{_LORA_ROWS}", f"{model_name}:t0",
                      f"{model_name}:t1"):
                await _drive_spec_one(s, url, m, content,
                                      gen_tokens, True)
            st2 = await _get_state(s, url)

        def tps(runs):
            return sum(n for _, n in runs) / sum(d for d, _ in runs)

        mix_tps, base_tps = tps(mix), tps(base)
        churn = _lora_ab_fields(st1, st2)
        return {
            "lora_mix_tps": round(mix_tps, 1),
            "lora_base_tps": round(base_tps, 1),
            "lora_mix_vs_base": (round(mix_tps / base_tps, 4)
                                 if base_tps else 0.0),
            "lora_mix_spread": round(_spread(
                [n / d for d, n in mix if d > 0]), 3),
            "lora_ab_reps": reps * requests_per_rep,
            "lora_zoo": _LORA_ZOO,
            "lora_rows": st2.get("adapter_rows", 0),
            # timed-rep telemetry: loads/evictions should be ZERO here
            # (the rotation is resident) and compiles zero everywhere
            **_lora_ab_fields(st0, st1),
            "lora_churn_loads": churn["adapter_loads"],
            "lora_churn_evictions": churn["adapter_evictions"],
            "lora_churn_hot_compiles": churn["lora_hot_compiles"],
        }

    try:
        return asyncio.run(run())
    finally:
        stop()


# -- structured leg: grammar-constrained decoding A/B (ISSUE 9) -----------

#: the leg's response_format schema: ONE bounded string field, so the
#: whole output length is structurally bounded (~53 chars) and every
#: completed constrained response MUST parse + validate — and grammar
#: transitions (each ~2 rollback windows on the random-weight model,
#: where the model never anticipates structure) stay a small fraction
#: of the content tokens, which is what a real model's traffic looks
#: like at the window level
_STRUCT_SCHEMA = {
    "type": "object",
    "properties": {"report": {"type": "string", "maxLength": 40}},
    "required": ["report"],
    "additionalProperties": False,
}
#: worst-case constrained output: {"report":"<40>"} = 53 tokens (byte
#: tokenizer) + EOS; plain traffic generates the same volume so the
#: phase throughputs compare token-for-token
_STRUCT_GEN = 54
_STRUCT_MAX = 80


def _structured_ab_fields(st0: dict, st1: dict) -> dict:
    """Constraint telemetry of one timed phase from /state deltas —
    pure so test_bench_smoke can unit-test the field derivation."""
    return {
        "structured_requests": (st1.get("constraint_requests", 0)
                                - st0.get("constraint_requests", 0)),
        "structured_rollbacks": (st1.get("constraint_rollbacks", 0)
                                 - st0.get("constraint_rollbacks", 0)),
        "structured_mask_updates": (
            st1.get("constraint_mask_updates", 0)
            - st0.get("constraint_mask_updates", 0)),
        "structured_hot_compiles": (st1.get("xla_compiles", 0)
                                    - st0.get("xla_compiles", 0)),
        "structured_grammars": st1.get("constraint_grammars", 0),
    }


async def _drive_struct_openloop(s, url: str, model_name: str,
                                 trace: list[dict]) -> tuple:
    """Fire one open-loop arrival schedule of chat requests (items:
    {t, constrained}) — arrival-time-fired regardless of completions.
    Returns (wall_s, total_completion_tokens, [constrained texts])."""
    texts: list = []
    totals: list[int] = []

    async def one(item: dict, t0: float) -> None:
        delay = t0 + item["t"] - time.perf_counter()
        if delay > 0:
            await asyncio.sleep(delay)
        body = {
            "model": model_name,
            "messages": [{"role": "user",
                          "content": f"arrival {item['i']:03d} hi"}],
            "temperature": 0.0,
            "logit_bias": {"97": 100},
        }
        if item["constrained"]:
            body["max_tokens"] = _STRUCT_MAX
            body["response_format"] = {
                "type": "json_schema",
                "json_schema": {"name": "r", "schema": _STRUCT_SCHEMA}}
        else:
            body["max_tokens"] = _STRUCT_GEN
        async with s.post(url + "/v1/chat/completions",
                          json=body) as resp:
            assert resp.status == 200, (resp.status,
                                        (await resp.read())[:300])
            got = await resp.json()
        totals.append(got["usage"]["completion_tokens"])
        if item["constrained"]:
            texts.append(got["choices"][0]["message"]["content"])

    t0 = time.perf_counter()
    await asyncio.gather(*(one(it, t0) for it in trace))
    wall = time.perf_counter() - t0
    return wall, sum(totals), texts


def structured_numbers(reps: int = 2, arrivals: int = 12,
                       constrained_frac: float = 0.25) -> dict:
    """The ``--ab structured`` leg (ISSUE 9): the same seeded open-loop
    arrival schedule against ONE tpuserve child (speculation on — the
    batch genuinely mixes constrained/plain/speculating slots), once
    with ``constrained_frac`` of arrivals asking for json_schema output
    and once all-plain at matched token volume. Criteria: every
    completed constrained response parses AND validates against the
    requested schema; zero hot XLA compiles across the timed phases;
    mixed/plain throughput ratio prices the constraint bookkeeping
    (mask row updates + rollback windows). Per-request byte-identity of
    unconstrained traffic is the f32-rig test's claim
    (tests/test_constrained_serving.py), not re-measured here."""
    import random as _random

    import aiohttp

    model_name = "bench-struct-tiny"
    # f32 params + f32 KV like the prefix leg: XLA:CPU repacks bf16
    # weight arguments per call, and an f32→bf16 K/V scatter is a
    # deprecated implicit cast (bf16 stays the default on TPU)
    url, stop = _start_tpuserve_subproc(
        model_name, CPU_CFG, "", batch=8,
        k_steps=int(os.environ.get("AIGW_BENCH_CPU_K", "4")),
        engine={"spec_tokens": 4, "kv_cache_dtype": "float32"},
        param_dtype="float32")

    def mk_trace(seed: int, constrained: bool) -> list[dict]:
        # seeded staggered arrivals (~0.25s mean gap): open-loop — the
        # schedule never waits on completions, so slots stay saturated
        # and the ratio measures steady-state per-window overhead. The
        # SAME seed yields the same arrival times for both phases;
        # constrained flags land on a seeded random subset.
        rng = _random.Random(seed)
        times, t = [], 0.0
        for _ in range(arrivals):
            times.append(t)
            t += rng.uniform(0.05, 0.45)
        n_con = round(arrivals * constrained_frac) if constrained else 0
        con = set(rng.sample(range(arrivals), n_con))
        return [{"t": times[i], "i": i, "constrained": i in con}
                for i in range(arrivals)]

    async def run() -> dict:
        await _wait_health(url, 1200)
        timeout = aiohttp.ClientTimeout(total=1200)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            # off-the-clock warm pass: compiles the decode page bucket,
            # prefill rung, and the mask-update program; caches the
            # grammar
            await _drive_struct_openloop(s, url, model_name, [
                {"t": 0.0, "i": 0, "constrained": True},
                {"t": 0.0, "i": 1, "constrained": False},
            ])
            st0 = await _get_state(s, url)
            mixed, plain, all_texts = [], [], []
            for rep in range(reps):
                w, n, texts = await _drive_struct_openloop(
                    s, url, model_name, mk_trace(1000 + rep, True))
                mixed.append((w, n))
                all_texts.extend(texts)
                w, n, _ = await _drive_struct_openloop(
                    s, url, model_name, mk_trace(1000 + rep, False))
                plain.append((w, n))
            st1 = await _get_state(s, url)
        ok = sum(1 for t in all_texts if _struct_valid(t))
        ratios = [(nm / wm) / (np_ / wp)
                  for (wm, nm), (wp, np_) in zip(mixed, plain)
                  if wm > 0 and wp > 0 and np_ > 0]
        return {
            "structured_mixed_tps": round(
                sum(n for _, n in mixed) / sum(w for w, _ in mixed), 1),
            "structured_plain_tps": round(
                sum(n for _, n in plain) / sum(w for w, _ in plain), 1),
            "structured_mixed_vs_plain": round(_median(ratios), 4),
            "structured_ratio_spread": round(_spread(ratios), 3),
            "structured_valid_frac": (round(ok / len(all_texts), 4)
                                      if all_texts else 0.0),
            "structured_constrained_responses": len(all_texts),
            "structured_ab_reps": reps,
            **_structured_ab_fields(st0, st1),
        }

    try:
        return asyncio.run(run())
    finally:
        stop()


def _struct_valid(text: str) -> bool:
    from aigw_tpu.tpuserve.constrain import validate_instance

    try:
        return validate_instance(_STRUCT_SCHEMA, json.loads(text))
    except ValueError:
        return False


# -- open-loop load generation + fleet legs (ISSUE 8; ROADMAP 5) ----------

def _poisson_trace(seed: int, n: int, rate_hz: float,
                   prompt_lens=(48, 96, 160), gen_lens=(8, 16, 24),
                   tenants=("",), burst_frac=0.25) -> list[dict]:
    """TokenSim-style open-loop arrival trace: Poisson inter-arrivals
    with a ``burst_frac`` share of zero-gap (bursty) arrivals, mixed
    prompt/output lengths and tenants. Seeded — the SAME trace drives
    both sides of an A/B so the comparison is over identical load."""
    import random

    rng = random.Random(seed)
    t = 0.0
    out = []
    for i in range(n):
        gap = (0.0 if (i > 0 and rng.random() < burst_frac)
               else rng.expovariate(rate_hz))
        t += gap
        out.append({
            "at": t,
            "prompt_len": rng.choice(list(prompt_lens)),
            "gen": rng.choice(list(gen_lens)),
            "tenant": rng.choice(list(tenants)),
            "i": i,
        })
    return out


#: histogram parsing generalized into the live gateway monitor (ISSUE
#: 12, obs/slomon.py) — the bench keeps its old name as an alias; the
#: shared parser additionally tolerates extra labels, so the gateway's
#: replica-labeled /fleet/metrics federation parses with the same code
_parse_hist_buckets = slomon.parse_hist_buckets


def _sum_hists(hists: list[dict]) -> dict[str, int]:
    out: dict[str, int] = {}
    for h in hists:
        for le, c in h.items():
            out[le] = out.get(le, 0) + c
    return out


def _goodput_fields(h0: dict, h1: dict, slo_ms: float, arrivals: int,
                    shed: int, prefix: str) -> dict:
    """Goodput-under-SLO over one capture window, computed from the
    SERVER-SIDE TTFT histograms (cumulative bucket deltas), not client
    clocks: under_slo = requests whose engine-observed TTFT landed in a
    bucket ≤ the SLO. goodput = under_slo / arrivals — shed and
    never-served requests count against goodput by construction.
    The bucket math is the shared slomon implementation the gateway's
    live burn-rate monitor runs on the same histograms."""
    total = h1.get("+Inf", 0) - h0.get("+Inf", 0)
    u = (slomon.under_slo_count(h1, slo_ms)
         - slomon.under_slo_count(h0, slo_ms))
    return {
        f"{prefix}_arrivals": arrivals,
        f"{prefix}_served": total,
        f"{prefix}_shed": shed,
        f"{prefix}_under_slo": u,
        f"{prefix}_goodput": round(u / arrivals, 4) if arrivals else 0.0,
    }


async def _get_text(s, url: str, path: str) -> str:
    async with s.get(url + path) as resp:
        return (await resp.read()).decode()


async def _ttft_hists(s, urls: list[str]) -> dict[str, int]:
    """Summed server-side TTFT histogram over a replica set."""
    hs = []
    for u in urls:
        hs.append(_parse_hist_buckets(
            await _get_text(s, u, "/metrics"), "tpuserve_ttft_hist_ms"))
    return _sum_hists(hs)


async def _drive_openloop(s, url: str, model: str, trace: list[dict],
                          tag: str = "",
                          payload_extra: dict | None = None) -> dict:
    """Fire the trace open-loop (each request at its arrival time, not
    gated on completions) as streaming /v1/completions; returns
    client-side outcome counts. Server-side goodput comes from the
    replica histograms — the client numbers here are for shed
    accounting and sanity, not latency claims. ``payload_extra`` merges
    extra request fields (the metering leg opts streams into the usage
    tail frame with it — the meter rides that frame to the gateway)."""
    res = {"completed": 0, "shed": 0, "shed_retry_after": 0,
           "errors": 0, "client_ttft_ms": []}

    async def one(item: dict, t0: float) -> None:
        delay = t0 + item["at"] - time.perf_counter()
        if delay > 0:
            await asyncio.sleep(delay)
        n = item["prompt_len"]
        text = (f"{tag}{item['i']:03d}" + "y" * n)[: n - 1]
        payload = {
            "model": model, "prompt": text,
            "max_tokens": item["gen"], "temperature": 0.0,
            "stream": True, "logit_bias": {"97": 100},
        }
        if payload_extra:
            payload.update(payload_extra)
        headers = ({"x-aigw-tenant": item["tenant"]}
                   if item["tenant"] else {})
        sent = time.perf_counter()
        try:
            async with s.post(url + "/v1/completions", json=payload,
                              headers=headers) as resp:
                if resp.status == 429:
                    res["shed"] += 1
                    if resp.headers.get("retry-after"):
                        res["shed_retry_after"] += 1
                    await resp.read()
                    return
                if resp.status != 200:
                    res["errors"] += 1
                    await resp.read()
                    return
                first = -1.0
                async for line in resp.content:
                    line = line.strip()
                    if first < 0 and line.startswith(b"data: ") \
                            and b'"text"' in line:
                        first = 1e3 * (time.perf_counter() - sent)
                res["completed"] += 1
                if first > 0:
                    res["client_ttft_ms"].append(first)
        except (aiohttp.ClientError, asyncio.TimeoutError):
            res["errors"] += 1

    import aiohttp  # noqa: F811 — bench imports lazily by convention
    t0 = time.perf_counter()
    await asyncio.gather(*(one(it, t0) for it in trace))
    return res


def _start_gateway_cfg(backend_extra: dict, endpoints: list[str],
                       top_extra: dict | None = None):
    """`aigw run` subprocess over a replica POOL with arbitrary backend
    knobs (picker_mode / slo_ttft_ms / migration …) plus optional
    TOP-LEVEL config keys (usage block, llm_request_costs). Returns
    (url, stop_fn)."""
    import tempfile

    import yaml

    cfg = {
        "version": "v1",
        "backends": [dict(
            {"name": "pool", "schema": "OpenAI",
             "endpoints": endpoints, "picker_poll_interval": 0.2},
            **backend_extra)],
        "routes": [{"name": "bench", "rules": [{"backends": ["pool"]}]}],
    }
    if top_extra:
        cfg.update(top_extra)
    f = tempfile.NamedTemporaryFile("w", suffix=".yaml", delete=False)
    yaml.safe_dump(cfg, f)
    f.close()
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "aigw_tpu", "run", f.name,
         "--port", str(port)],
        cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
    )

    def stop():
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        os.unlink(f.name)

    return f"http://127.0.0.1:{port}", stop


def slo_routing_numbers(arrivals: int = 36, reps: int = 3) -> dict:
    """The ``slo_routing`` A/B leg: the SAME seeded open-loop arrival
    trace against two gateway configurations over the same two-replica
    pool — picker_mode "slo" (predictive TTFT routing + shed) vs
    "static" (the classic score sum) — goodput-under-SLO computed from
    the replicas' server-side TTFT histograms. The pool is deliberately
    heterogeneous: replica A is a PREFILL straggler — every prompt pads
    to the full 512-token bucket (one rung, min bucket = max seq: the
    shape a degraded or misconfigured replica takes in production) —
    which static occupancy/queue scoring cannot see until queues have
    already built, while the phase histograms price it into every
    prediction up front. Reps interleave the two gateways over fresh
    trace seeds; both gateways see identical load."""
    import aiohttp

    model_name = "bench-slo-tiny"
    k = int(os.environ.get("AIGW_BENCH_CPU_K", "4"))
    engine_common = {"num_pages": 64, "max_queued_requests": 64}
    # replica A: the prefill straggler; replica B: the healthy sibling
    url_a, stop_a = _start_tpuserve_subproc(
        model_name, CPU_CFG, "", batch=2, k_steps=k,
        engine=dict(engine_common, min_prefill_bucket=512,
                    prefill_bucket_rungs=1),
        page=16)
    url_b, stop_b = _start_tpuserve_subproc(
        model_name, CPU_CFG, "", batch=2, k_steps=k,
        engine=dict(engine_common, min_prefill_bucket=32),
        page=16)
    addrs = [u[len("http://"):] for u in (url_a, url_b)]

    async def run() -> dict:
        await _wait_health(url_a, 1200)
        await _wait_health(url_b, 1200)
        timeout = aiohttp.ClientTimeout(total=1200)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            # calibrate the SLO budget off the healthy replica's
            # unloaded TTFT (sequential, direct). The same pass also
            # seeds BOTH replicas' phase histograms — the slo gateway
            # must know A is a prefill straggler from its first poll,
            # not discover it by routing the first rep's traffic there
            # (a replica with no data predicts 0 = idle)
            cal = []
            for i in range(3):
                tr = [{"at": 0.0, "prompt_len": 96, "gen": 4,
                       "tenant": "", "i": i}]
                r = await _drive_openloop(s, url_b, model_name, tr,
                                          tag=f"c{i}")
                cal.extend(r["client_ttft_ms"])
                await _drive_openloop(s, url_a, model_name, tr,
                                      tag=f"a{i}")
            # off the clock: drive every prompt/gen shape the timed
            # traces use DIRECTLY at each child, so rep 0 never pays an
            # XLA compile mid-capture (the first capture previously
            # measured compile stalls, not routing)
            for url, tg in ((url_b, "wb"), (url_a, "wa")):
                warm = _poisson_trace(seed=999, n=12, rate_hz=4.0,
                                      gen_lens=(2, 4, 6))
                await _drive_openloop(s, url, model_name, warm, tag=tg)
            base = _median(cal) if cal else 500.0
            slo_ms = max(300.0, 4.0 * base)

            out: dict = {"slo_routing_slo_ms": round(slo_ms, 1),
                         "slo_routing_reps": reps}
            acc: dict[str, list] = {"slo": [], "static": []}
            sheds = {"slo": 0, "static": 0}
            retry_ok = 0
            for rep in range(reps):
                for mode in ("slo", "static"):
                    extra = {"picker_mode": mode} if mode == "slo" \
                        else {}
                    if mode == "slo":
                        extra["slo_ttft_ms"] = slo_ms
                        # short burn windows so the live monitor closes
                        # several during the trace — the fleet fields
                        # below carry real burn data, not -1 sentinels
                        extra["slo_window_s"] = 5.0
                    gw, stop_gw = _start_gateway_cfg(extra, addrs)
                    try:
                        await _wait_health(gw, 120)
                        # let the picker poll real telemetry first
                        await asyncio.sleep(1.0)
                        trace = _poisson_trace(
                            seed=1000 + rep, n=arrivals, rate_hz=1.5,
                            gen_lens=(2, 4, 6))
                        h0 = await _ttft_hists(s, [url_a, url_b])
                        res = await _drive_openloop(
                            s, gw, model_name, trace,
                            tag=f"{mode[0]}{rep}")
                        h1 = await _ttft_hists(s, [url_a, url_b])
                        g = _goodput_fields(
                            h0, h1, slo_ms, arrivals, res["shed"],
                            prefix="x")
                        acc[mode].append(g["x_goodput"])
                        sheds[mode] += res["shed"]
                        retry_ok += res["shed_retry_after"]
                        if mode == "slo" and rep == reps - 1:
                            # fleet observability plane (ISSUE 12):
                            # carry the aggregated fleet snapshot +
                            # live burn-rate fields into the capture
                            async with s.get(gw + "/fleet/state") as r:
                                out.update(_fleet_obs_fields(
                                    await r.json(), "slo_fleet"))
                    finally:
                        stop_gw()
            # PAIRED comparison: rep i's slo and static captures ran
            # the same seeded trace, so per-rep goodput ratios cancel
            # trace difficulty and host drift; the median ratio is the
            # claim, the pooled goodputs are context
            ratios = [s_g / st_g for s_g, st_g in
                      zip(acc["slo"], acc["static"]) if st_g > 0]
            slo_g = sum(acc["slo"]) / len(acc["slo"])
            static_g = sum(acc["static"]) / len(acc["static"])
            out.update({
                "slo_goodput": round(slo_g, 4),
                "static_goodput": round(static_g, 4),
                "slo_vs_static_goodput": (
                    round(_median(ratios), 4) if ratios
                    else (round(slo_g / static_g, 4) if static_g
                          else 0.0)),
                "slo_goodput_by_rep": [round(x, 4) for x in acc["slo"]],
                "static_goodput_by_rep": [round(x, 4)
                                          for x in acc["static"]],
                "slo_shed": sheds["slo"],
                "static_shed": sheds["static"],
                "slo_shed_retry_after": retry_ok,
                "slo_goodput_spread": round(_spread(acc["slo"]), 3),
                "static_goodput_spread": round(
                    _spread(acc["static"]), 3),
            })
            return out

    try:
        return asyncio.run(run())
    finally:
        stop_a()
        stop_b()


# -- fleet observability plane (ISSUE 12) ---------------------------------

def _fleet_obs_fields(snapshot: dict, prefix: str = "fleet") -> dict:
    """Flatten a gateway /fleet/state payload into bench JSON fields —
    future BENCH_r* captures carry fleet-level telemetry (health
    counts, worst pressure, live burn rate), not just client-side
    ratios (unit-tested in tests/test_bench_smoke.py)."""
    ru = snapshot.get("fleet") or {}
    slo: dict = {}
    health: dict[str, str] = {}
    for b in (snapshot.get("backends") or {}).values():
        slo = slo or (b.get("slo") or {})
        for addr, r in (b.get("replicas") or {}).items():
            health[addr] = (r.get("health") or {}).get("state", "?")
    return {
        f"{prefix}_replicas_up": int(ru.get("replicas_up", 0)),
        f"{prefix}_replicas_degraded": int(
            ru.get("replicas_degraded", 0)),
        f"{prefix}_replicas_down": int(ru.get("replicas_down", 0)),
        f"{prefix}_slots_free": int(ru.get("slots_free", 0)),
        f"{prefix}_slots_total": int(ru.get("slots_total", 0)),
        f"{prefix}_kv_occupancy_worst": float(
            ru.get("kv_occupancy_worst", 0.0)),
        f"{prefix}_hbm_frac_worst": float(
            ru.get("device_memory_frac_worst", 0.0)),
        f"{prefix}_goodput": float(slo.get("goodput", -1.0)),
        f"{prefix}_burn_rate": float(slo.get("burn_rate", -1.0)),
        f"{prefix}_overshoot_sustained": bool(
            slo.get("sustained_overshoot", False)),
        f"{prefix}_health": dict(sorted(health.items())),
        f"{prefix}_decisions": int(
            snapshot.get("decisions_recorded", 0)),
    }


def _fleet_fields_from_states(st0s: dict, st1s: dict, slo_ms: float,
                              prefix: str = "fleet") -> dict:
    """Fleet-level fields for the gateway-LESS legs (kv_tier drives
    replicas directly): goodput/burn over the leg window from the
    replicas' cumulative /state ttft_hist_buckets deltas — the same
    slomon math the gateway monitor runs — plus occupancy/slot
    rollups from the closing snapshots."""
    h0 = slomon.sum_buckets(
        (st or {}).get("ttft_hist_buckets") or {} for st in st0s.values())
    h1 = slomon.sum_buckets(
        (st or {}).get("ttft_hist_buckets") or {} for st in st1s.values())
    served = slomon.total_count(h1) - slomon.total_count(h0)
    under = (slomon.under_slo_count(h1, slo_ms)
             - slomon.under_slo_count(h0, slo_ms))
    goodput = under / served if served > 0 else -1.0
    occ = [float((st or {}).get("kv_occupancy", 0.0))
           for st in st1s.values()]
    return {
        f"{prefix}_slo_ms": round(slo_ms, 1),
        f"{prefix}_served": served,
        f"{prefix}_goodput": round(goodput, 4),
        f"{prefix}_burn_rate": (
            round((1.0 - goodput) / 0.05, 4) if goodput >= 0 else -1.0),
        f"{prefix}_kv_occupancy_worst": round(max(occ, default=0.0), 4),
        f"{prefix}_slots_total": sum(
            int((st or {}).get("max_slots", 0)) for st in st1s.values()),
    }


# -- decode_fused leg: fused decode step + quantized KV pages (ISSUE 13) --

# Leg model with head_dim 64 — the smallest serving-shaped head at
# which the int8 capacity claim holds ((64 + 4) / 128 = 0.53; TINY's
# D=16 pays 0.625 because the f32 scale is amortized over too few
# elements and would falsify a true claim).
_FUSED_CFG = llama.LlamaConfig(
    vocab_size=2048, dim=256, n_layers=4, n_heads=4, n_kv_heads=2,
    ffn_dim=512, max_seq_len=1024, rope_theta=10000.0,
)
_FUSED_PAGE = 32


async def _drive_decode_one(s, url: str, model: str, content: str,
                            gen_tokens: int) -> tuple:
    """One greedy sequential streaming chat; returns
    (duration_s, tokens, joined_text) — the text is the
    stream-identity probe."""
    payload = {
        "model": model,
        "messages": [{"role": "user", "content": content}],
        "max_tokens": gen_tokens,
        "temperature": 0.0,
        "stream": True,
        "stream_options": {"include_usage": True},
    }
    t0 = time.perf_counter()
    usage = None
    parts: list[str] = []
    async with s.post(url + "/v1/chat/completions", json=payload) as resp:
        assert resp.status == 200, resp.status
        while True:
            line = await resp.content.readline()
            if not line:
                break
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            data = line[6:]
            if data == b"[DONE]":
                break
            ev = json.loads(data)
            if ev.get("usage"):
                usage = ev["usage"]
            ch = ev.get("choices") or []
            delta = (ch[0].get("delta") or {}) if ch else {}
            if delta.get("content"):
                parts.append(delta["content"])
    dur = time.perf_counter() - t0
    ntok = (usage or {}).get("completion_tokens") or len(parts)
    return dur, ntok, "".join(parts)


def decode_fused_numbers(reps: int = 3, requests_per_rep: int = 4,
                         gen_tokens: int = 64) -> dict:
    """The ``--ab decode_fused`` leg (ISSUE 13): decode-heavy greedy
    streaming chats against THREE tpuserve children on identical
    seeded traffic, requests interleaved so host drift cancels:

    - **fused vs chained** (both f32 KV): the same prompts must stream
      IDENTICAL text (the f32-rig equivalence, measured over the real
      HTTP surface), zero hot compiles on either child, and the tok/s
      ratio is reported. On this CPU backend the fused child runs the
      XLA page-walk reference, so the ratio is bookkeeping parity —
      the kernel's HBM win needs the TPU capture (tools/tpu_capture).
    - **int8-KV fused vs native**: capacity — kv_bytes_per_token and
      the pool-bytes ratio from /state (claim: ≤ 0.55x) — and quality,
      as greedy-token agreement against the native child's streams on
      the same prompts (the PR 9 int4-weight smoke's role, measured
      end-to-end)."""
    import aiohttp

    model_name = "bench-fused-tiny"
    k = int(os.environ.get("AIGW_BENCH_CPU_K", "4"))
    engine_common = {"min_prefill_bucket": 32, "num_pages": 96,
                     "max_queued_requests": 64,
                     "warm_decode_buckets": 3}
    children = []

    def start(backend: str, kv_dtype: str, pdtype: str):
        url, stop = _start_tpuserve_subproc(
            model_name, _FUSED_CFG, "", batch=4, k_steps=k,
            engine=dict(engine_common, decode_backend=backend,
                        kv_cache_dtype=kv_dtype),
            page=_FUSED_PAGE, param_dtype=pdtype)
        children.append(stop)
        return url

    url_fu = start("fused", "float32", "float32")
    url_ch = start("auto", "float32", "float32")
    url_q8 = start("fused", "int8", "float32")

    prompts = [f"decode fused probe {i} " + "ab" * 24
               for i in range(requests_per_rep)]

    async def run() -> dict:
        await _wait_health(url_fu, 1200)
        await _wait_health(url_ch, 1200)
        await _wait_health(url_q8, 1200)
        timeout = aiohttp.ClientTimeout(total=1200)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            # off the clock: compile whatever the warm pass missed
            for url in (url_fu, url_ch, url_q8):
                await _drive_decode_one(s, url, model_name,
                                        prompts[0], gen_tokens)
            st_fu0 = await _get_state(s, url_fu)
            st_ch0 = await _get_state(s, url_ch)
            fu, ch, q8 = [], [], []
            for _rep in range(reps):
                for p in prompts:
                    fu.append(await _drive_decode_one(
                        s, url_fu, model_name, p, gen_tokens))
                    ch.append(await _drive_decode_one(
                        s, url_ch, model_name, p, gen_tokens))
                    q8.append(await _drive_decode_one(
                        s, url_q8, model_name, p, gen_tokens))
            st_fu1 = await _get_state(s, url_fu)
            st_ch1 = await _get_state(s, url_ch)
            st_q8 = await _get_state(s, url_q8)

        def tps(runs):
            return sum(n for _, n, _t in runs) / sum(
                d for d, _n, _t in runs)

        identical = all(a[2] == b[2] for a, b in zip(fu, ch))

        def agree(a: str, b: str) -> float:
            n = max(len(a), len(b), 1)
            same = sum(1 for x, y in zip(a, b) if x == y)
            return same / n

        q8_agree = (sum(agree(a[2], b[2]) for a, b in zip(q8, ch))
                    / max(len(q8), 1))
        ratio = tps(fu) / tps(ch) if tps(ch) else 0.0
        return {
            "decode_fused_tps": round(tps(fu), 1),
            "decode_chained_tps": round(tps(ch), 1),
            "decode_fused_ratio": round(ratio, 4),
            "decode_fused_identical_streams": identical,
            "decode_fused_impl": st_fu1.get("decode_attn_impl", ""),
            "decode_fused_hot_compiles": (
                st_fu1.get("xla_compiles", 0)
                - st_fu0.get("xla_compiles", 0)),
            "decode_chained_hot_compiles": (
                st_ch1.get("xla_compiles", 0)
                - st_ch0.get("xla_compiles", 0)),
            "kv_int8_bytes_per_token": st_q8.get(
                "kv_bytes_per_token", 0),
            "kv_native_bytes_per_token": st_ch1.get(
                "kv_bytes_per_token", 0),
            # native child runs f32 KV (the rig); quote the claim
            # against the SERVING dtype: bf16 = f32 / 2
            "kv_int8_bytes_ratio_vs_bf16": round(
                st_q8.get("kv_bytes_per_token", 0)
                / max(st_ch1.get("kv_bytes_per_token", 1) / 2.0, 1e-9),
                4),
            "kv_int8_greedy_agreement": round(q8_agree, 4),
            "decode_fused_ab_reps": reps * requests_per_rep,
        }

    try:
        return asyncio.run(run())
    finally:
        for stop in children:
            stop()


async def _warm_openloop_shapes(s, url: str, model: str, tag: str,
                                gen_lens=(2, 4, 6)) -> None:
    """Off the clock: compile every shape a timed open-loop trace can
    use — every (prompt_len, gen) combo deterministically, simultaneous
    PAIRS over every prompt-length combination (batch=2 children
    coalesce admissions into group shapes the spaced pass never
    reaches), and a bursty pass for arrival-timing-dependent geometry.
    Shared by the fleet_obs and fleet_ctl legs — their hot-compile
    tripwires must measure the telemetry/control path, not first-use
    compiles."""
    combos = [(pl, g) for pl in (48, 96, 160) for g in gen_lens]
    warm = [{"at": 0.3 * i, "prompt_len": pl, "gen": g,
             "tenant": "", "i": i}
            for i, (pl, g) in enumerate(combos)]
    await _drive_openloop(s, url, model, warm, tag=tag)
    lens = (48, 96, 160)
    duos = [(a, b) for i, a in enumerate(lens) for b in lens[i:]]
    pairs = [{"at": 0.8 * j, "prompt_len": pl, "gen": gen_lens[0],
              "tenant": "", "i": 100 + 2 * j + kk}
             for j, (a, b) in enumerate(duos)
             for kk, pl in enumerate((a, b))]
    await _drive_openloop(s, url, model, pairs, tag=tag + "p")
    burst = _poisson_trace(seed=998, n=10, rate_hz=4.0,
                           gen_lens=gen_lens)
    await _drive_openloop(s, url, model, burst, tag=tag + "b")


def fleet_obs_numbers(reps: int = 3, arrivals: int = 20) -> dict:
    """The ``--ab fleet_obs`` leg (ISSUE 12): observability must be
    ~free. The SAME seeded open-loop trace through two gateway
    configurations over the same healthy two-replica pool — fleet_obs
    ON (decision ring recording every pick + the burn-rate monitor
    chewing polled histograms + a federation scraper hammering
    /fleet/metrics and /fleet/state at 4 Hz throughout) vs fleet_obs
    OFF (no ring, no monitor, no scraping). The claim: throughput
    ratio ≥ 0.95 and ZERO hot XLA compiles from the telemetry path."""
    import aiohttp

    model_name = "bench-fleetobs-tiny"
    k = int(os.environ.get("AIGW_BENCH_CPU_K", "4"))
    # warm_decode_buckets: decode programs re-trace per pow2 page-table
    # width (the PR 10 lesson) — without pre-compiling the ladder the
    # timed reps pay first-use decode compiles that would masquerade as
    # an observability tax in the hot-compile tripwire
    engine = {"num_pages": 64, "max_queued_requests": 64,
              "min_prefill_bucket": 32, "warm_decode_buckets": 7}
    url_a, stop_a = _start_tpuserve_subproc(
        model_name, CPU_CFG, "", batch=2, k_steps=k, engine=engine,
        page=16)
    url_b, stop_b = _start_tpuserve_subproc(
        model_name, CPU_CFG, "", batch=2, k_steps=k, engine=engine,
        page=16)
    addrs = [u[len("http://"):] for u in (url_a, url_b)]

    async def scrape_loop(s, gw: str, stop_evt: asyncio.Event) -> int:
        n = 0
        while not stop_evt.is_set():
            try:
                async with s.get(gw + "/fleet/metrics") as r:
                    await r.read()
                async with s.get(gw + "/fleet/state") as r:
                    await r.json()
                n += 1
            except (aiohttp.ClientError, asyncio.TimeoutError):
                pass
            await asyncio.sleep(0.25)
        return n

    async def run() -> dict:
        await _wait_health(url_a, 1200)
        await _wait_health(url_b, 1200)
        timeout = aiohttp.ClientTimeout(total=1200)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            # off the clock: compile every shape the timed traces use
            # (combos + coalesced pairs + bursty pass — the shared
            # open-loop warm helper)
            for url, tg in ((url_a, "wa"), (url_b, "wb")):
                await _warm_openloop_shapes(s, url, model_name, tg)
            xla0 = -1
            tput: dict[str, list] = {"on": [], "off": []}
            scrapes = 0
            snap: dict = {}
            for rep in range(reps):
                if rep == 1:
                    # compile tripwire anchored AFTER rep 0: the first
                    # on/off pair soaks whatever first-use geometry the
                    # deterministic warm above still missed (arrival-
                    # timing-dependent coalescing), so the steady-state
                    # reps isolate compiles the OBSERVABILITY path adds
                    # — which must be zero
                    xla0 = sum([(await _get_state(s, u)
                                 ).get("xla_compiles", 0)
                                for u in (url_a, url_b)])
                for mode in ("on", "off"):
                    extra = ({"slo_window_s": 2.0} if mode == "on"
                             else {"fleet_obs": False})
                    gw, stop_gw = _start_gateway_cfg(extra, addrs)
                    try:
                        await _wait_health(gw, 120)
                        await asyncio.sleep(1.0)  # first polls land
                        trace = _poisson_trace(
                            seed=1300 + rep, n=arrivals, rate_hz=3.0,
                            gen_lens=(2, 4, 6))
                        stop_evt = asyncio.Event()
                        scraper = (asyncio.create_task(
                            scrape_loop(s, gw, stop_evt))
                            if mode == "on" else None)
                        t0 = time.perf_counter()
                        res = await _drive_openloop(
                            s, gw, model_name, trace,
                            tag=f"{mode[:1]}{rep}")
                        wall = time.perf_counter() - t0
                        stop_evt.set()
                        if scraper is not None:
                            scrapes += await scraper
                            snap = await (await s.get(
                                gw + "/fleet/state")).json()
                        tput[mode].append(res["completed"] / wall)
                    finally:
                        stop_gw()
            xla1 = sum([(await _get_state(s, u)).get("xla_compiles", 0)
                        for u in (url_a, url_b)])
            if xla0 < 0:
                xla0 = xla1  # reps == 1: no steady-state window
        ratios = [a / b for a, b in zip(tput["on"], tput["off"])
                  if b > 0]
        out = {
            "fleet_obs_vs_off": round(_median(ratios), 4) if ratios
            else 0.0,
            "fleet_obs_vs_off_by_rep": [round(r, 4) for r in ratios],
            "fleet_obs_spread": round(_spread(tput["on"]), 3),
            "fleet_off_spread": round(_spread(tput["off"]), 3),
            "fleet_obs_hot_compiles": int(xla1 - xla0),
            "fleet_obs_scrapes": scrapes,
            "fleet_obs_reps": reps,
            "fleet_obs_arrivals": arrivals,
        }
        out.update(_fleet_obs_fields(snap, "fleet_obs"))
        return out

    try:
        return asyncio.run(run())
    finally:
        stop_a()
        stop_b()


def metering_numbers(reps: int = 3, arrivals: int = 20) -> dict:
    """The ``--ab metering`` leg (ISSUE 20): engine-truth usage
    metering must be ~free. The SAME seeded open-loop trace through
    two gateway configurations over the same healthy two-replica pool
    — metering ON (engine MeterRecords journaled into a 2s-window
    ledger, a CostProgram pricing every request through the new meter
    variables, /usage polled at 4 Hz throughout) vs metering OFF
    (``usage: {enabled: false}``, no cost programs). The claim:
    throughput ratio ≥ 0.95 and ZERO hot XLA compiles from the
    metering path; the on-leg also cross-checks ledger totals against
    the replicas' meter_* counters (exact decode-token reconciliation
    rides tier-1 — here it is a live smoke)."""
    import aiohttp

    model_name = "bench-metering-tiny"
    k = int(os.environ.get("AIGW_BENCH_CPU_K", "4"))
    engine = {"num_pages": 64, "max_queued_requests": 64,
              "min_prefill_bucket": 32, "warm_decode_buckets": 7}
    url_a, stop_a = _start_tpuserve_subproc(
        model_name, CPU_CFG, "", batch=2, k_steps=k, engine=engine,
        page=16)
    url_b, stop_b = _start_tpuserve_subproc(
        model_name, CPU_CFG, "", batch=2, k_steps=k, engine=engine,
        page=16)
    addrs = [u[len("http://"):] for u in (url_a, url_b)]
    #: the on-leg's top-level config: tight ledger windows plus a cost
    #: expression over the NEW meter variables (decode + padded prefill
    #: + residency) so the priced path is on the clock, not a stub
    metering_cfg = {
        "usage": {"window_s": 2.0, "budgets": {"bench": 1e9}},
        "llm_request_costs": [{
            "metadata_key": "tpu_cost",
            "type": "Expression",
            "expression": ("decode_tokens * 2 + prefill_padded_tokens"
                           " + int(kv_page_byte_seconds)"),
        }],
    }

    async def usage_loop(s, gw: str, stop_evt: asyncio.Event) -> int:
        n = 0
        while not stop_evt.is_set():
            try:
                async with s.get(gw + "/usage") as r:
                    await r.json()
                async with s.get(gw + "/metrics") as r:
                    await r.read()
                n += 1
            except (aiohttp.ClientError, asyncio.TimeoutError):
                pass
            await asyncio.sleep(0.25)
        return n

    async def run() -> dict:
        await _wait_health(url_a, 1200)
        await _wait_health(url_b, 1200)
        timeout = aiohttp.ClientTimeout(total=1200)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            for url, tg in ((url_a, "wa"), (url_b, "wb")):
                await _warm_openloop_shapes(s, url, model_name, tg)
            xla0 = -1
            tput: dict[str, list] = {"on": [], "off": []}
            polls = 0
            usage_snap: dict = {}
            for rep in range(reps):
                if rep == 1:
                    # compile tripwire anchored AFTER rep 0 (same
                    # discipline as fleet_obs: the first pair soaks
                    # arrival-timing-dependent first-use geometry, so
                    # steady-state isolates compiles METERING adds —
                    # which must be zero)
                    xla0 = sum([(await _get_state(s, u)
                                 ).get("xla_compiles", 0)
                                for u in (url_a, url_b)])
                for mode in ("on", "off"):
                    top = (metering_cfg if mode == "on"
                           else {"usage": {"enabled": False}})
                    gw, stop_gw = _start_gateway_cfg({}, addrs,
                                                     top_extra=top)
                    try:
                        await _wait_health(gw, 120)
                        await asyncio.sleep(1.0)  # first polls land
                        trace = _poisson_trace(
                            seed=2000 + rep, n=arrivals, rate_hz=3.0,
                            gen_lens=(2, 4, 6),
                            tenants=("bench", "team-b"))
                        stop_evt = asyncio.Event()
                        poller = (asyncio.create_task(
                            usage_loop(s, gw, stop_evt))
                            if mode == "on" else None)
                        t0 = time.perf_counter()
                        # both legs request the usage tail frame so the
                        # traces stay byte-identical; only the on-leg
                        # has a ledger to mine the meter into
                        res = await _drive_openloop(
                            s, gw, model_name, trace,
                            tag=f"m{mode[:1]}{rep}",
                            payload_extra={"stream_options": {
                                "include_usage": True}})
                        wall = time.perf_counter() - t0
                        stop_evt.set()
                        if poller is not None:
                            polls += await poller
                            usage_snap = await (await s.get(
                                gw + "/usage")).json()
                        tput[mode].append(res["completed"] / wall)
                    finally:
                        stop_gw()
            xla1 = sum([(await _get_state(s, u)).get("xla_compiles", 0)
                        for u in (url_a, url_b)])
            if xla0 < 0:
                xla0 = xla1  # reps == 1: no steady-state window
            # live reconciliation smoke for the LAST on-leg gateway:
            # its ledger's record count must equal the trace size (one
            # MeterRecord per finished request, exactly once)
            totals = (usage_snap.get("totals") or {})
        ratios = [a / b for a, b in zip(tput["on"], tput["off"])
                  if b > 0]
        return {
            "metering_vs_off": round(_median(ratios), 4) if ratios
            else 0.0,
            "metering_vs_off_by_rep": [round(r, 4) for r in ratios],
            "metering_on_spread": round(_spread(tput["on"]), 3),
            "metering_off_spread": round(_spread(tput["off"]), 3),
            "metering_hot_compiles": int(xla1 - xla0),
            "metering_usage_polls": polls,
            "metering_ledger_records": int(totals.get("records", 0)),
            "metering_ledger_decode_tokens": int(
                totals.get("decode_tokens", 0)),
            "metering_ledger_cost": int(totals.get("cost", 0)),
            "metering_records_expected": arrivals,
            "metering_reps": reps,
            "metering_arrivals": arrivals,
        }

    try:
        return asyncio.run(run())
    finally:
        stop_a()
        stop_b()


def _classify_stream(status: int, data_lines: list[bytes],
                     aborted: bool) -> str:
    """Outcome of one streamed request under churn (ISSUE 14):

    - ``complete`` — the stream reached its ``[DONE]`` terminal;
    - ``typed_error`` — a clean, client-parseable failure: a non-200
      JSON error response, or an SSE ``{"error": ...}`` event ending
      the stream (the gateway's mid-stream failure contract);
    - ``torn`` — the connection died (or the stream just stopped)
      without either. Torn streams are the DROPPED count the fleet_ctl
      acceptance criterion requires to be zero.
    """
    if status != 200:
        return "typed_error"
    if any(ln.strip() == b"[DONE]" for ln in data_lines):
        return "complete"
    if aborted:
        return "torn"
    for ln in data_lines:
        try:
            ev = json.loads(ln)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(ev, dict) and "error" in ev:
            return "typed_error"
    return "torn"


async def _drive_openloop_strict(s, url: str, model: str,
                                 trace: list[dict],
                                 tag: str = "") -> dict:
    """Open-loop driver with torn-stream accounting: like
    ``_drive_openloop`` but every arrival is classified complete /
    typed_error / torn via :func:`_classify_stream` — the chaos legs'
    zero-dropped-streams claim is the ``torn`` count staying zero
    while replicas are killed under the trace."""
    import aiohttp  # noqa: F811

    res: dict = {"complete": 0, "typed_error": 0, "torn": 0,
                 "client_ttft_ms": []}

    async def one(item: dict, t0: float) -> None:
        delay = t0 + item["at"] - time.perf_counter()
        if delay > 0:
            await asyncio.sleep(delay)
        n = item["prompt_len"]
        text = (f"{tag}{item['i']:03d}" + "y" * n)[: n - 1]
        payload = {
            "model": model, "prompt": text,
            "max_tokens": item["gen"], "temperature": 0.0,
            "stream": True, "logit_bias": {"97": 100},
        }
        status = 0
        data_lines: list[bytes] = []
        aborted = False
        first = -1.0
        sent = time.perf_counter()
        try:
            async with s.post(url + "/v1/completions",
                              json=payload) as resp:
                status = resp.status
                if status != 200:
                    await resp.read()
                else:
                    async for line in resp.content:
                        line = line.strip()
                        if not line.startswith(b"data: "):
                            continue
                        d = line[6:]
                        data_lines.append(d)
                        if first < 0 and b'"text"' in d:
                            first = 1e3 * (time.perf_counter() - sent)
        except (aiohttp.ClientError, asyncio.TimeoutError):
            aborted = True
        res[_classify_stream(status, data_lines, aborted)] += 1
        if first > 0:
            res["client_ttft_ms"].append(first)

    t0 = time.perf_counter()
    await asyncio.gather(*(one(it, t0) for it in trace))
    return res


def fleet_ctl_numbers(arrivals: int = 24) -> dict:
    """The ``--ab fleet_ctl`` leg (ISSUE 14): the fleet control plane
    under injected churn. The seeded open-loop trace runs against a
    2-replica pool behind a controller-enabled gateway while the
    harness (1) ``kill -9``s replica A mid-decode — the crash case: the
    controller must detect it, re-route, and launch a replacement
    through the LocalProcessLauncher; (2) floods the survivor until the
    SLO monitor's sustained-overshoot flag trips — the controller must
    scale out. The claims: dropped (torn) streams == 0 — every client
    sees a complete stream or a typed error event — goodput recovers to
    ≥0.9× the pre-event window in a bounded, reported time, and the
    SURVIVING replica pays zero hot XLA compiles throughout."""
    import aiohttp

    from tools import chaos

    model_name = "bench-fleetctl-tiny"
    k = int(os.environ.get("AIGW_BENCH_CPU_K", "4"))
    engine = {"num_pages": 64, "max_queued_requests": 64,
              "min_prefill_bucket": 32, "warm_decode_buckets": 7}
    child_spec = {
        "model": model_name,
        "cfg": {key: getattr(CPU_CFG, key) for key in (
            "vocab_size", "dim", "n_layers", "n_heads", "n_kv_heads",
            "ffn_dim", "max_seq_len", "rope_theta")},
        "batch": 2, "page": 16, "k": k, "quantize": "",
        "engine": engine, "param_dtype": "", "lora": {}, "tp": 1,
    }
    rep_a = chaos.spawn_replica(child_spec)
    rep_b = chaos.spawn_replica(child_spec)
    gen_lens = (3, 5, 7)

    gw, stop_gw = _start_gateway_cfg({
        "picker_poll_interval": 0.1,
        "migration": True,
        "migration_queue_depth": 2,
        # static picker mode: slo_ttft_ms feeds ONLY the burn-rate
        # monitor (no shedding) — the scale-out predicate's SLO
        "slo_ttft_ms": 150.0,
        "slo_window_s": 1.5,
        "slo_burn_windows": 2,
        "controller": {
            "min_replicas": 2, "max_replicas": 3,
            "tick_s": 0.25, "down_grace_s": 0.5,
            "scale_cooldown_s": 3.0,
            # scale-in disabled for the leg (it would retire the
            # replica the tripwire is anchored on)
            "idle_ticks": 1_000_000,
            "drain_timeout_s": 30.0,
            "launcher": {"kind": "local", "spec": child_spec,
                         "term_grace_s": 5.0},
        },
    }, [rep_a.address, rep_b.address])

    async def run() -> dict:
        await _wait_health(rep_a.url, 1200)
        await _wait_health(rep_b.url, 1200)
        timeout = aiohttp.ClientTimeout(total=1200)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            for url, tg in ((rep_a.url, "fa"), (rep_b.url, "fb")):
                await _warm_openloop_shapes(s, url, model_name, tg,
                                            gen_lens=gen_lens)
            await _wait_health(gw, 180)
            await asyncio.sleep(1.2)  # first polls land

            async def ctl_state() -> dict:
                snap = await (await s.get(gw + "/fleet/state")).json()
                return (snap["backends"]["pool"].get("controller")
                        or {})

            # the survivor's compile tripwire anchors AFTER its warm
            xla0 = (await _get_state(s, rep_b.url)).get(
                "xla_compiles", 0)

            outcomes = {"complete": 0, "typed_error": 0, "torn": 0}

            def tally(r: dict) -> None:
                for key in outcomes:
                    outcomes[key] += r[key]

            # ---- pre-event window --------------------------------
            pre = await _drive_openloop_strict(
                s, gw, model_name,
                _poisson_trace(seed=1400, n=arrivals, rate_hz=3.0,
                               gen_lens=gen_lens), tag="pr")
            tally(pre)
            goodput_pre = pre["complete"] / arrivals

            # ---- crash injection: kill -9 A mid-decode -----------
            evt_trace = _poisson_trace(seed=1401, n=arrivals,
                                       rate_hz=3.0, gen_lens=gen_lens)
            kill_at = evt_trace[arrivals // 3]["at"] + 0.15
            t_kill = [0.0]

            async def assassin() -> None:
                await asyncio.sleep(kill_at)
                t_kill[0] = time.perf_counter()
                rep_a.kill9()

            evt, _ = await asyncio.gather(
                _drive_openloop_strict(s, gw, model_name, evt_trace,
                                       tag="ev"),
                assassin())
            tally(evt)
            goodput_event = evt["complete"] / arrivals

            # ---- failover: detection + replacement launch --------
            deadline = time.perf_counter() + 900
            ctl: dict = {}
            while time.perf_counter() < deadline:
                ctl = await ctl_state()
                if (ctl.get("counters", {}).get("failovers", 0) >= 1
                        and len(ctl.get("replicas_live") or ()) >= 2):
                    break
                await asyncio.sleep(0.5)
            failovers = ctl.get("counters", {}).get("failovers", 0)
            launched = ctl.get("counters", {}).get("launch_failures", 0)

            # ---- goodput recovery probes -------------------------
            recovery_s = -1.0
            probe_n = 8
            probe_seed = 1500
            while time.perf_counter() - t_kill[0] < 900:
                probe = await _drive_openloop_strict(
                    s, gw, model_name,
                    _poisson_trace(seed=probe_seed, n=probe_n,
                                   rate_hz=4.0, gen_lens=gen_lens),
                    tag=f"p{probe_seed % 100}")
                probe_seed += 1
                tally(probe)
                if probe["complete"] / probe_n >= 0.9 * goodput_pre:
                    recovery_s = time.perf_counter() - t_kill[0]
                    break

            # ---- triggered scale-out: flood past the SLO ---------
            scale_outs = 0
            for flood_round in range(4):
                flood = await _drive_openloop_strict(
                    s, gw, model_name,
                    _poisson_trace(seed=1600 + flood_round, n=20,
                                   rate_hz=12.0, gen_lens=gen_lens),
                    tag=f"fl{flood_round}")
                tally(flood)
                ctl = await ctl_state()
                scale_outs = ctl.get("counters", {}).get(
                    "scale_outs", 0)
                if scale_outs >= 1:
                    break
                await asyncio.sleep(1.6)  # let a window close

            xla1 = (await _get_state(s, rep_b.url)).get(
                "xla_compiles", 0)
            ctl = await ctl_state()
            snap = await (await s.get(gw + "/fleet/state")).json()
        return {
            "fleet_ctl_arrivals": sum(outcomes.values()),
            "fleet_ctl_complete": outcomes["complete"],
            "fleet_ctl_typed_errors": outcomes["typed_error"],
            # the acceptance criterion: zero torn/hung streams — every
            # client saw a complete stream or a typed error event
            "fleet_ctl_dropped_streams": outcomes["torn"],
            "fleet_ctl_goodput_pre": round(goodput_pre, 4),
            "fleet_ctl_goodput_event": round(goodput_event, 4),
            "fleet_ctl_recovery_s": round(recovery_s, 2),
            "fleet_ctl_recovered": recovery_s >= 0,
            "fleet_ctl_failovers": failovers,
            "fleet_ctl_scale_outs": scale_outs,
            "fleet_ctl_launch_failures": launched,
            "fleet_ctl_replicas_live": len(
                ctl.get("replicas_live") or ()),
            "fleet_ctl_lifecycle_events": len(ctl.get("events") or ()),
            "fleet_ctl_survivor_hot_compiles": int(xla1 - xla0),
            "fleet_ctl_fleet_up": snap.get("fleet", {}).get(
                "replicas_up", 0),
        }

    try:
        return asyncio.run(run())
    finally:
        stop_gw()  # gateway cleanup terminates launcher-owned children
        rep_a.kill9()
        rep_b.term(timeout=30)


async def _disagg_migrate_once(s, url_a: str, url_b: str, model: str,
                               prompt_len: int, tag: str) -> dict:
    """One migration rep: stream on A, export after the first tokens,
    import+resume on B. Returns {resume_ttft_ms, tokens_total,
    pages_moved, text}."""
    import aiohttp  # noqa: F811

    n = prompt_len
    text = (tag + "z" * n)[: n - 1]
    payload = {"model": model, "prompt": text, "max_tokens": 40,
               "temperature": 0.0, "stream": True,
               "logit_bias": {"97": 100}}
    pieces: list[str] = []
    rid = ""
    export = None
    async with s.post(url_a + "/v1/completions", json=payload) as resp:
        assert resp.status == 200, resp.status
        rid = resp.headers.get("x-aigw-request-id", "")
        got = 0
        async for line in resp.content:
            line = line.strip()
            if not line.startswith(b"data: ") or line[6:] == b"[DONE]":
                continue
            ev = json.loads(line[6:])
            ch = ev.get("choices") or []
            if ch and ch[0].get("text"):
                pieces.append(ch[0]["text"])
                got += 1
                if got == 2 and export is None:
                    async with s.post(url_a + "/migrate/export",
                                      json={"request_id": rid}) as r:
                        assert r.status == 200, (r.status,
                                                 await r.read())
                        export = await r.json()
        # stream ends at the cut with no terminal frames
    assert export is not None
    t0 = time.perf_counter()
    first = -1.0
    async with s.post(url_b + "/migrate/import", json=export) as r:
        assert r.status == 200, (r.status, await r.read())
        async for line in r.content:
            line = line.strip()
            if not line.startswith(b"data: ") or line[6:] == b"[DONE]":
                continue
            ev = json.loads(line[6:])
            ch = ev.get("choices") or []
            if ch and ch[0].get("text"):
                if first < 0:
                    first = 1e3 * (time.perf_counter() - t0)
                pieces.append(ch[0]["text"])
    return {
        "resume_ttft_ms": first,
        "pages_moved": len(export["pages"]),
        "cont_tokens": len(export["blob"]["tokens"]),
        "text": "".join(pieces),
    }


async def _disagg_cold_ttft(s, url: str, model: str, n_tokens: int,
                            tag: str) -> float:
    """Cold-prefill TTFT control: a fresh prompt of the SAME total
    length the migrated session had at its resume."""
    text = (tag + "q" * n_tokens)[: n_tokens - 1]
    payload = {"model": model, "prompt": text, "max_tokens": 4,
               "temperature": 0.0, "stream": True,
               "logit_bias": {"97": 100}}
    t0 = time.perf_counter()
    async with s.post(url + "/v1/completions", json=payload) as resp:
        assert resp.status == 200, resp.status
        async for line in resp.content:
            line = line.strip()
            if line.startswith(b"data: ") and b'"text"' in line:
                return 1e3 * (time.perf_counter() - t0)
    return -1.0


def disagg_numbers(reps: int = 5, prompt_len: int = 288,
                   arrivals: int = 24) -> dict:
    """The ``disagg`` A/B leg (ISSUE 8), two tpuserve replicas:

    1. **Resume vs cold** (the headline): per interleaved rep, a
       session streams on A, is exported after its first tokens, and
       resumes on B through /migrate/import — resume TTFT (import +
       page adoption + ≤1-page tail recompute + first token) against a
       cold-prefill TTFT for a fresh prompt of the same total length on
       the same replica. Target: resume ≤ 0.6× cold.
    2. **Gateway orchestration under open-loop load**: the same Poisson
       trace through a migration-ON gateway vs a migration-OFF gateway
       over the pool (replica A deliberately slow-queued), reporting
       server-side goodput and the migration counters — proves the
       DECISION loop (deep prefill queue → hand off to the
       decode-leaning sibling) fires under real load."""
    import aiohttp

    model_name = "bench-disagg-tiny"
    k = int(os.environ.get("AIGW_BENCH_CPU_K", "4"))
    engine_common = {"min_prefill_bucket": 32, "num_pages": 96,
                     "max_queued_requests": 64,
                     "kv_cache_dtype": "float32"}
    # replica A deliberately single-slot: under the open-loop pass its
    # admission queue deepens fast (the disaggregation trigger), while
    # the interleaved resume-vs-cold reps below are sequential and
    # don't care about batch width
    url_a, stop_a = _start_tpuserve_subproc(
        model_name, _PREFIX_CFG, "", batch=1, k_steps=k,
        engine=dict(engine_common), page=_PREFIX_PAGE,
        param_dtype="float32")
    url_b, stop_b = _start_tpuserve_subproc(
        model_name, _PREFIX_CFG, "", batch=2, k_steps=k,
        engine=dict(engine_common), page=_PREFIX_PAGE,
        param_dtype="float32")
    addrs = [u[len("http://"):] for u in (url_a, url_b)]

    async def run() -> dict:
        await _wait_health(url_a, 1200)
        await _wait_health(url_b, 1200)
        timeout = aiohttp.ClientTimeout(total=1200)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            # off the clock: warm both children's resume + cold shapes
            await _disagg_migrate_once(s, url_a, url_b, model_name,
                                       prompt_len, "w0")
            await _disagg_cold_ttft(s, url_b, model_name,
                                    prompt_len + 8, "w1")
            resume_t, cold_t, pages = [], [], []
            for rep in range(reps):
                m = await _disagg_migrate_once(
                    s, url_a, url_b, model_name, prompt_len,
                    f"m{rep:02d}")
                if m["resume_ttft_ms"] > 0:
                    resume_t.append(m["resume_ttft_ms"])
                pages.append(m["pages_moved"])
                c = await _disagg_cold_ttft(
                    s, url_b, model_name, m["cont_tokens"],
                    f"k{rep:02d}")
                if c > 0:
                    cold_t.append(c)
            st_a = await _get_state(s, url_a)
            st_b = await _get_state(s, url_b)

            # gateway orchestration under open-loop load, mig on/off:
            # the same seeded trace through a migration-ON gateway and a
            # migration-OFF gateway, goodput from the replicas'
            # server-side TTFT histograms against a 2×cold-TTFT budget
            gw_fields: dict = {}
            gw_slo = 2.0 * _median(cold_t) if cold_t else 1000.0
            gw_fields["disagg_gw_slo_ms"] = round(gw_slo, 1)
            for mig in (True, False):
                extra = {"migration": mig, "migration_queue_depth": 1,
                         "migration_young_tokens": 48}
                gw, stop_gw = _start_gateway_cfg(extra, addrs)
                try:
                    await _wait_health(gw, 120)
                    await asyncio.sleep(1.0)
                    trace = _poisson_trace(
                        seed=77, n=arrivals, rate_hz=2.0,
                        prompt_lens=(96, 160, 224),
                        gen_lens=(16, 24, 32))
                    h0 = await _ttft_hists(s, [url_a, url_b])
                    res = await _drive_openloop(
                        s, gw, model_name, trace,
                        tag="g1" if mig else "g0")
                    h1 = await _ttft_hists(s, [url_a, url_b])
                    gw_fields.update(_goodput_fields(
                        h0, h1, gw_slo, arrivals, res["shed"],
                        prefix="disagg_gw_on" if mig
                        else "disagg_gw_off"))
                finally:
                    stop_gw()
            st_a2 = await _get_state(s, url_a)
            st_b2 = await _get_state(s, url_b)
            gw_fields["disagg_gw_migrations"] = (
                st_a2["migrations_out"] + st_b2["migrations_out"]
                - st_a["migrations_out"] - st_b["migrations_out"])

        resume = _median(resume_t)
        cold = _median(cold_t)
        return {
            "disagg_resume_ttft_ms_p50": round(resume, 1),
            "disagg_cold_ttft_ms_p50": round(cold, 1),
            "disagg_resume_vs_cold": (round(resume / cold, 4)
                                      if cold else 0.0),
            "disagg_resume_spread": round(_spread(resume_t), 3),
            "disagg_cold_spread": round(_spread(cold_t), 3),
            "disagg_pages_moved": _median([float(p) for p in pages]),
            "disagg_migrations_out": st_a["migrations_out"],
            "disagg_migrations_in": st_b["migrations_in"],
            "disagg_ab_reps": reps,
            **gw_fields,
        }

    try:
        return asyncio.run(run())
    finally:
        stop_a()
        stop_b()


# -- kv_tier leg: fleet KV memory hierarchy (ISSUE 11) -------------------

#: Leg model: compute-heavy relative to its KV bytes (wide dim + big
#: ffn, few KV heads) — on the CPU rig the cross-replica fetch pays in
#: page BYTES (b64 wire + import scatter) while the cold prefill pays
#: in COMPUTE, and this shape keeps the two costs in the same relation
#: they have on a real chip (where prefill compute dwarfs DCN page
#: movement). max_seq 512, 16-token pages.
_KVTIER_CFG = llama.LlamaConfig(
    vocab_size=8192, dim=1024, n_layers=6, n_heads=16, n_kv_heads=2,
    ffn_dim=4096, max_seq_len=512, rope_theta=10000.0,
)
_KVTIER_HEAD = 128  # shared-prefix head chars (8 full 16-token pages)


def _kvtier_ab_fields(st0: dict, st1: dict,
                      prefix: str = "kvtier") -> dict:
    """Counter deltas between two /state snapshots — the spill/revive/
    fetch churn and the hot-compile tripwire the kv_tier leg reports
    (unit-tested in tests/test_bench_smoke.py)."""

    def d(k: str) -> int:
        return int(st1.get(k, 0)) - int(st0.get(k, 0))

    return {
        f"{prefix}_spills": d("kv_spills"),
        f"{prefix}_revives": d("kv_revives"),
        f"{prefix}_fetches_in": d("kv_fetches_in"),
        f"{prefix}_fetches_out": d("kv_fetches_out"),
        f"{prefix}_fetch_pages_in": d("kv_fetch_pages_in"),
        f"{prefix}_fetch_pages_out": d("kv_fetch_pages_out"),
        f"{prefix}_hot_compiles": d("xla_compiles"),
    }


async def _kvtier_openloop(s, url: str, model: str, head: str,
                           arrivals: int, headers: dict,
                           tag: str) -> list[float]:
    """Shared-prefix open-loop burst: ``arrivals`` streaming
    completions whose prompts share ``head``, fired at staggered
    arrival times. Returns per-arrival TTFT ms in arrival order —
    arrival 0 pays the fetch (warm fleet) or the full prefill (cold
    fleet); later arrivals hit the replica's own cache either way."""

    async def one(i: int, t0: float) -> float:
        await asyncio.sleep(max(0.0, t0 + 0.08 * i - time.perf_counter()))
        payload = {"model": model,
                   "prompt": head + f" {tag}-u{i:02d}",
                   "max_tokens": 4, "temperature": 0.0,
                   "stream": True, "logit_bias": {"97": 100}}
        ts = time.perf_counter()
        async with s.post(url + "/v1/completions", json=payload,
                          headers=headers) as resp:
            assert resp.status == 200, resp.status
            async for line in resp.content:
                line = line.strip()
                if line.startswith(b"data: ") and b'"text"' in line:
                    return 1e3 * (time.perf_counter() - ts)
        return -1.0

    t0 = time.perf_counter()
    return list(await asyncio.gather(
        *(one(i, t0) for i in range(arrivals))))


def kv_tier_numbers(reps: int = 3, arrivals: int = 4) -> dict:
    """The ``--ab kv_tier`` leg (ISSUE 11), two tpuserve replicas with
    the host spill tier on:

    1. **Warm fleet vs cold fleet** (the headline): per interleaved
       rep, replica A is primed with a fresh shared-prefix head, then
       the same shared-prefix open-loop burst runs against replica B
       twice — once with A named in x-aigw-kv-peers (warm fleet:
       arrival 0 fetches A's pages over /kv/pages and resumes) and
       once with an unprimed head and no peers (cold fleet: arrival 0
       pays the full prefill). Target: first-arrival TTFT ratio ≤ 0.6.
    2. **Spill→revive churn on A** (off the clock): distinct floods
       overflow A's pool so the primed chains spill to host RAM, a
       re-ask revives one — counters prove the tier moved pages both
       ways, and the /state xla_compiles delta across a second churn
       cycle proves the whole spill/revive/fetch path stays off the
       compiler (CompileTracker tripwire)."""
    import aiohttp

    model_name = "bench-kvtier-tiny"
    k = int(os.environ.get("AIGW_BENCH_CPU_K", "4"))
    engine_common = {"min_prefill_bucket": 32,
                     "kv_cache_dtype": "float32",
                     "kv_host_bytes": 1 << 30,
                     "warm_decode_buckets": 5,
                     "max_queued_requests": 64}
    url_a, stop_a = _start_tpuserve_subproc(
        model_name, _KVTIER_CFG, "", batch=2, k_steps=k,
        engine=dict(engine_common, num_pages=64), page=_PREFIX_PAGE,
        param_dtype="float32")
    url_b, stop_b = _start_tpuserve_subproc(
        model_name, _KVTIER_CFG, "", batch=4, k_steps=k,
        engine=dict(engine_common, num_pages=128), page=_PREFIX_PAGE,
        param_dtype="float32")
    addr_a = url_a[len("http://"):]

    def head_of(tag: str) -> str:
        return (tag + "s" * _KVTIER_HEAD)[:_KVTIER_HEAD]

    async def prime(s, tag: str) -> None:
        payload = {"model": model_name,
                   "prompt": head_of(tag) + " prime",
                   "max_tokens": 2, "temperature": 0.0,
                   "logit_bias": {"97": 100}}
        async with s.post(url_a + "/v1/completions",
                          json=payload) as resp:
            assert resp.status == 200, resp.status

    async def run() -> dict:
        await _wait_health(url_a, 1200)
        await _wait_health(url_b, 1200)
        timeout = aiohttp.ClientTimeout(total=1200)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            peers = {"x-aigw-kv-peers": addr_a}
            # off the clock: one full warm+cold cycle compiles every
            # shape the timed reps will touch (fetch import rungs and
            # the suffix resume on B, prefill buckets on both)
            await prime(s, "w0")
            # a second identical prime is a partial prefix hit: it
            # compiles A's offset-resume program off the clock (the
            # churn's revive re-ask resumes the same way)
            await prime(s, "w0")
            await asyncio.sleep(1.0)  # A's digest refresh
            await _kvtier_openloop(s, url_b, model_name, head_of("w0"),
                                   arrivals, peers, "w0")
            await _kvtier_openloop(s, url_b, model_name, head_of("wx"),
                                   arrivals, {}, "wx")

            st_b0 = await _get_state(s, url_b)
            st_a0 = await _get_state(s, url_a)
            warm_t, cold_t, warm_rest = [], [], []
            for rep in range(reps):
                await prime(s, f"h{rep:02d}")
                await asyncio.sleep(1.0)
                w = await _kvtier_openloop(
                    s, url_b, model_name, head_of(f"h{rep:02d}"),
                    arrivals, peers, f"w{rep:02d}")
                c = await _kvtier_openloop(
                    s, url_b, model_name, head_of(f"c{rep:02d}"),
                    arrivals, {}, f"c{rep:02d}")
                if w[0] > 0:
                    warm_t.append(w[0])
                warm_rest += [t for t in w[1:] if t > 0]
                if c[0] > 0:
                    cold_t.append(c[0])
            st_b1 = await _get_state(s, url_b)
            st_a1 = await _get_state(s, url_a)
            fields = _kvtier_ab_fields(st_b0, st_b1, "kvtier_b")
            fields.update(_kvtier_ab_fields(st_a0, st_a1, "kvtier_a"))
            # fleet-level telemetry for the capture (ISSUE 12): this
            # leg has no gateway, so the fleet rollup + goodput over
            # the timed window come straight from the replicas'
            # /state histograms via the shared slomon math (1s TTFT
            # reference SLO — a fixed yardstick, not a target)
            fields.update(_fleet_fields_from_states(
                {"a": st_a0, "b": st_b0}, {"a": st_a1, "b": st_b1},
                slo_ms=1000.0, prefix="kvtier_fleet"))

            # spill→revive churn on A (off the clock): overflow the
            # 64-page pool so the primed chains spill, revive one
            for i in range(8):
                await prime(s, f"f{i:02d}")
            st_c0 = await _get_state(s, url_a)
            for i in range(8, 12):
                await prime(s, f"f{i:02d}")
            await prime(s, "h00")  # re-ask: revives if spilled
            st_c1 = await _get_state(s, url_a)
            fields.update(_kvtier_ab_fields(st_c0, st_c1,
                                            "kvtier_churn"))

        warm = _median(warm_t)
        cold = _median(cold_t)
        return {
            "kvtier_warm_ttft_ms_p50": round(warm, 1),
            "kvtier_cold_ttft_ms_p50": round(cold, 1),
            "kvtier_warm_vs_cold": (round(warm / cold, 4)
                                    if cold else 0.0),
            "kvtier_warm_spread": round(_spread(warm_t), 3),
            "kvtier_cold_spread": round(_spread(cold_t), 3),
            # later arrivals of the warm bursts: the replica's own
            # cache serves them — the shared-prefix economics at
            # steady state
            "kvtier_warm_rest_ttft_ms_p50": round(
                _median(warm_rest), 1) if warm_rest else 0.0,
            "kvtier_ab_reps": reps,
            "kvtier_arrivals": arrivals,
            **fields,
        }

    try:
        return asyncio.run(run())
    finally:
        stop_a()
        stop_b()


_LONGCTX_SP = 8
#: page_size % sp == 0 (16 % 8) so the chunked-sp suffix program builds;
#: 4096-token sessions at 16-token pages = 256 pages — long enough that
#: a monolithic sp prefill visibly starves queued short arrivals on the
#: CPU backend, short enough that the leg fits the bench budget
_LONGCTX_CFG = llama.LlamaConfig(
    vocab_size=2048, dim=256, n_layers=4, n_heads=8, n_kv_heads=8,
    ffn_dim=512, max_seq_len=4096, rope_theta=10000.0,
)
_LONGCTX_PAGE = 16
_LONGCTX_LONG = 3500    # long-prompt tokens (byte tokenizer)
_LONGCTX_SHORT = 48     # interactive prompt tokens (< sp_prefill_min)
_LONGCTX_HEAD = 1664    # resume head: 104 full 16-token pages
_LONGCTX_CONT = 512     # continuation ≥ sp_prefill_min → sp offset resume


def _p95(xs: list[float]) -> float:
    s = sorted(xs)
    if not s:
        return 0.0
    return s[min(len(s) - 1, int(round(0.95 * (len(s) - 1))))]


async def _longctx_stream(s, url: str, model: str, prompt: str,
                          max_tokens: int) -> float:
    """One streaming completion; returns TTFT ms (awaits the full
    stream so the caller knows the session's slot is free after)."""
    payload = {"model": model, "prompt": prompt,
               "max_tokens": max_tokens, "temperature": 0.0,
               "stream": True, "logit_bias": {"97": 100}}
    ttft = -1.0
    t0 = time.perf_counter()
    async with s.post(url + "/v1/completions", json=payload) as resp:
        assert resp.status == 200, resp.status
        async for line in resp.content:
            line = line.strip()
            if (line.startswith(b"data: ") and b'"text"' in line
                    and ttft < 0):
                ttft = 1e3 * (time.perf_counter() - t0)
    return ttft


async def _longctx_cycle(s, url: str, model: str, tag: str,
                         arrivals: int) -> tuple[list[float], float]:
    """The decode-liveness probe: fire one long prompt, then — while
    its prefill is in flight — a concurrent burst of short interactive
    streams. Returns (interactive TTFTs ms, long TTFT ms). On the
    chunked child the shorts admit at the next chunk boundary; on the
    monolithic child they wait out the whole sharded prefill."""
    long_prompt = (f"{tag}L" + "x" * _LONGCTX_LONG)[:_LONGCTX_LONG]
    long_task = asyncio.ensure_future(
        _longctx_stream(s, url, model, long_prompt, 4))
    await asyncio.sleep(0.25)  # long prefill underway

    async def one(i: int) -> float:
        text = (f"{tag}i{i:02d} " + "q" * _LONGCTX_SHORT)
        return await _longctx_stream(s, url, model,
                                     text[:_LONGCTX_SHORT], 4)

    ttfts = list(await asyncio.gather(*(one(i)
                                        for i in range(arrivals))))
    long_ttft = await long_task
    return ttfts, long_ttft


async def _longctx_resume_cycle(s, url: str, model: str,
                                tag: str) -> tuple[float, float]:
    """Warm-resume vs cold on the chunked child: prime a page-aligned
    long head, re-ask head+continuation (prefix-cache partial hit →
    the sp chunk loop resumes at the adopted offset, only the ≥512-
    token suffix is computed), vs a cold prompt of the same total
    length. Returns (warm TTFT ms, cold TTFT ms)."""
    head = (f"{tag}h" + "s" * _LONGCTX_HEAD)[:_LONGCTX_HEAD]
    await _longctx_stream(s, url, model, head, 2)  # prime the chain
    warm = await _longctx_stream(
        s, url, model, head + "c" * _LONGCTX_CONT, 4)
    n = _LONGCTX_HEAD + _LONGCTX_CONT
    cold = await _longctx_stream(
        s, url, model, (f"{tag}x" + "z" * n)[:n], 4)
    return warm, cold


def longctx_numbers(reps: int = 3, arrivals: int = 4) -> dict:
    """The ``--ab longctx`` leg (ISSUE 17): the same long-context
    traffic against TWO sp=8 tpuserve children (8 virtual CPU devices)
    — sequence-sharded CHUNKED prefill vs the MONOLITHIC sp path. The
    portable claims:

    - **decode liveness / interactive TTFT**: short streams fired
      mid-long-prefill admit at chunk boundaries on the chunked child
      (``sp_interactive_admits`` counts them) instead of waiting out
      the whole sharded prefill — interactive TTFT p95 target ≥ 2×
      better chunked vs monolithic;
    - **offset resume**: re-asking a primed page-aligned head +
      continuation resumes the chunk loop at the adopted offset
      (``sp_resume_prefills``) — warm/cold TTFT ratio target ≤ 0.6;
    - **padding tax**: the chunk rung ladder keeps the sp path's
      padded_frac < 0.05 while the monolithic path pays the full
      top-rung residue;
    - **compile surface**: zero hot XLA compiles over the timed reps
      at long-context geometry (CompileTracker tripwire).

    Absolute ms is NOT the signal on CPU — ratios and counters are."""
    import aiohttp

    model_name = "bench-longctx-tiny"
    k = int(os.environ.get("AIGW_BENCH_CPU_K", "4"))
    engine_common = {
        "min_prefill_bucket": 32, "kv_cache_dtype": "float32",
        "max_queued_requests": 64, "num_pages": 768,
        # interactive arrivals must hit the queue immediately — the
        # leg measures chunk-boundary admission, not coalescing
        "admission_coalesce_ms": 0.0,
        # CPU-scale overrides: long prompts chunk at 256 tokens so a
        # 3500-token prefill has ~13 boundaries on a 1-core host
        "sp_prefill_min_tokens": 256, "sp_chunk_tokens": 256,
        "warm_decode_buckets": 4,
        # TTFT is the metric and the off-clock warm cycle absorbs the
        # shape compiles; the spec ladder would only widen the warm
        # surface and add draft nondeterminism to a random-weight rig
        "spec_tokens": 0,
    }
    env = {"XLA_FLAGS":
           f"--xla_force_host_platform_device_count={_LONGCTX_SP}"}
    url_c, stop_c = _start_tpuserve_subproc(
        model_name, _LONGCTX_CFG, "", batch=6, k_steps=k,
        engine=dict(engine_common, sp_prefill_mode="chunked"),
        page=_LONGCTX_PAGE, param_dtype="float32", sp=_LONGCTX_SP,
        env_extra=env)
    url_m, stop_m = _start_tpuserve_subproc(
        model_name, _LONGCTX_CFG, "", batch=6, k_steps=k,
        engine=dict(engine_common, sp_prefill_mode="monolithic"),
        page=_LONGCTX_PAGE, param_dtype="float32", sp=_LONGCTX_SP,
        env_extra=env)

    async def run() -> dict:
        await _wait_health(url_c, 1200)
        await _wait_health(url_m, 1200)
        timeout = aiohttp.ClientTimeout(total=1200)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            # off the clock: one full cycle per child compiles every
            # shape the timed reps touch (chunk rungs at each offset,
            # the monolithic top rung, interactive singletons, decode
            # page buckets, and the chunked child's resume suffix)
            await _longctx_cycle(s, url_c, model_name, "w", arrivals)
            await _longctx_cycle(s, url_m, model_name, "w", arrivals)
            await _longctx_resume_cycle(s, url_c, model_name, "w")

            st_c0 = await _get_state(s, url_c)
            st_m0 = await _get_state(s, url_m)
            c_int, m_int = [], []
            c_long, m_long = [], []
            warm_t, cold_t = [], []
            for rep in range(reps):
                ci, cl = await _longctx_cycle(
                    s, url_c, model_name, f"r{rep}", arrivals)
                mi, ml = await _longctx_cycle(
                    s, url_m, model_name, f"r{rep}", arrivals)
                c_int += [t for t in ci if t > 0]
                m_int += [t for t in mi if t > 0]
                c_long.append(cl)
                m_long.append(ml)
                w, c = await _longctx_resume_cycle(
                    s, url_c, model_name, f"r{rep}")
                if w > 0:
                    warm_t.append(w)
                if c > 0:
                    cold_t.append(c)
            st_c1 = await _get_state(s, url_c)
            st_m1 = await _get_state(s, url_m)

        def d(st0: dict, st1: dict, key: str) -> int:
            return int(st1.get(key, 0)) - int(st0.get(key, 0))

        ci95, mi95 = _p95(c_int), _p95(m_int)
        warm, cold = _median(warm_t), _median(cold_t)
        return {
            "longctx_sp": _LONGCTX_SP,
            "longctx_prompt_tokens": _LONGCTX_LONG,
            "longctx_max_seq_len": int(
                st_c1.get("max_seq_len", 0) or 0),
            "longctx_interactive_ttft_ms_p95_chunked": round(ci95, 1),
            "longctx_interactive_ttft_ms_p95_monolithic": round(
                mi95, 1),
            # ≥ 2.0 is the decode-liveness claim
            "longctx_interactive_gain": (round(mi95 / ci95, 4)
                                         if ci95 > 0 else 0.0),
            "longctx_long_ttft_ms_p50_chunked": round(
                _median(c_long), 1),
            "longctx_long_ttft_ms_p50_monolithic": round(
                _median(m_long), 1),
            "longctx_resume_ttft_ms_p50": round(warm, 1),
            "longctx_cold_ttft_ms_p50": round(cold, 1),
            # ≤ 0.6 is the offset-resume claim
            "longctx_resume_vs_cold": (round(warm / cold, 4)
                                       if cold else 0.0),
            "longctx_interactive_spread": round(_spread(c_int), 3),
            "longctx_resume_spread": round(_spread(warm_t), 3),
            "longctx_chunked_prefills": d(
                st_c0, st_c1, "sp_chunked_prefills"),
            "longctx_resume_prefills": d(
                st_c0, st_c1, "sp_resume_prefills"),
            "longctx_interactive_admits": d(
                st_c0, st_c1, "sp_interactive_admits"),
            "longctx_ab_reps": reps,
            "longctx_arrivals": arrivals,
            **_ragged_ab_fields(st_c0, st_c1, "longctx_chunked"),
            **_ragged_ab_fields(st_m0, st_m1, "longctx_monolithic"),
        }

    try:
        return asyncio.run(run())
    finally:
        stop_c()
        stop_m()


def _chip_responsive(timeout_s: float = 180.0) -> bool:
    """The axon tunnel can go down entirely (observed 2026-07-28); probe
    with a watchdog so the bench prints an honest line instead of hanging
    the driver."""
    done = threading.Event()
    result = {"ok": False}

    def probe():
        try:
            x = jnp.ones((128, 128)) @ jnp.ones((128, 128))
            x.block_until_ready()
            result["ok"] = True
        except Exception as e:  # fail fast with the real reason
            result["error"] = f"{type(e).__name__}: {e}"
        finally:
            done.set()

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    done.wait(timeout_s)
    if not result["ok"] and "error" in result:
        print(f"device probe failed: {result['error']}", file=sys.stderr)
    return result["ok"]


def _build_8b_int8():
    from aigw_tpu.models.quant import quantize_params

    cfg = llama.LlamaConfig(max_seq_len=1024)  # LLAMA3_8B shapes
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    params = quantize_params(params, consume=True)
    jax.block_until_ready(params)
    return params, cfg, "llama-3-8b-arch W8A16 int8", "bench-llama3-8b", \
        "int8"


def _build_fallback():
    params = llama.init_params(jax.random.PRNGKey(0), FALLBACK_CFG)
    jax.block_until_ready(params)
    return params, FALLBACK_CFG, "1.1B llama-arch bf16", "bench-llama-1b", ""


def _suite(params_holder, cfg, desc, model_name, quantize, batch,
           prompt_len, gen_tokens, label, k_steps=K_STEPS,
           reps=3, subproc=False) -> dict:
    """``params_holder`` is a one-element list so THIS frame owns the
    only reference — the caller must del its own binding. The weights
    are freed before the gateway leg's server builds its own copy (the
    8B model fits the chip once, not twice)."""
    params = params_holder.pop()
    raw = raw_ceiling_tokens_per_sec(params, cfg, batch, prompt_len,
                                     k_steps)
    engine_runs, engine_phases = engine_numbers(
        params, cfg, batch, prompt_len, gen_tokens, k_steps, reps=reps)
    engine = _median([r[0] for r in engine_runs])
    engine_ttft = _median([r[1] for r in engine_runs])
    engine_spread = _spread([r[0] for r in engine_runs])
    del params
    gc.collect()
    gw = gateway_numbers(model_name, cfg, quantize, batch, prompt_len,
                         gen_tokens, k_steps, reps=reps, subproc=subproc)
    spreads = (engine_spread, gw["direct_tps_spread"],
               gw["gateway_tps_spread"])
    return {
        "metric": (
            f"{label}gateway tokens/sec through `aigw run` → tpuserve "
            f"streaming /v1/chat/completions, {desc}, batch={batch}, "
            f"prompt={prompt_len}, paged KV; vs_baseline = gateway / "
            f"raw-JAX-K-step-scan ceiling (north star: ≥0.9 and "
            f"ttft_ms_p50 < 200); medians of {reps} interleaved reps"
        ),
        "value": round(gw["gateway_tps"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(gw["gateway_tps"] / raw, 4),
        "raw_ceiling": round(raw, 1),
        "ttft_ms_p50": round(gw["gateway_ttft_ms_p50"], 1),
        "engine_tokens_per_sec": round(engine, 1),
        "engine_vs_raw": round(engine / raw, 4),
        "engine_ttft_ms_p50": round(engine_ttft, 1),
        "serve_direct_tokens_per_sec": round(gw["direct_tps"], 1),
        "serve_direct_ttft_ms_p50": round(gw["direct_ttft_ms_p50"], 1),
        "gateway_ttft_minus_direct_ms": round(
            gw["gateway_ttft_ms_p50"] - gw["direct_ttft_ms_p50"], 1),
        "engine_tps_spread": round(engine_spread, 3),
        "direct_tps_spread": gw["direct_tps_spread"],
        "gateway_tps_spread": gw["gateway_tps_spread"],
        # engine-leg host-time phase breakdown (cumulative ms across the
        # warm request + all reps): which serving-path phase moved when
        # the headline does
        "prefill_ms": engine_phases["prefill_ms"],
        "transfer_ms": engine_phases["transfer_ms"],
        "emit_ms": engine_phases["emit_ms"],
        "first_emit_ms": engine_phases["first_emit_ms"],
        # serving-side distribution spreads (ISSUE 5): p50/p95/p99 read
        # from the replica's own phase histograms over the whole capture
        # (warm + all reps) — the interpretable tail behind the
        # client-measured medians above
        "ttft_hist_ms": gw.get("serve_phase_percentiles", {}).get(
            "ttft", {}),
        "per_token_hist_ms": gw.get("serve_phase_percentiles", {}).get(
            "decode_per_token", {}),
        "queue_wait_hist_ms": gw.get("serve_phase_percentiles", {}).get(
            "queue_wait", {}),
        # analytical MFU of the engine leg's decode rate (2·matmul
        # params + attention terms per token ÷ chip peak; v5e bf16 peak
        # unless AIGW_CHIP_PEAK_FLOPS overrides). A diagnostic on the
        # CPU backend; the same field becomes the on-chip headline MFU
        # (VERDICT r5 #2).
        "mfu": round(model_mfu(cfg, engine,
                               prompt_len + gen_tokens // 2), 8),
        "mfu_flops_per_token": round(model_flops_per_token(
            cfg, prompt_len + gen_tokens // 2)),
        "mfu_peak_flops": CHIP_PEAK_FLOPS,
        # the capture is trustworthy when every leg's reps agree within
        # 15% (r4 verdict: the engine leg once measured 44% below the
        # HTTP leg — pure harness variance committed as signal)
        "harness_stable": all(s <= 0.15 for s in spreads),
    }


def run_live() -> dict:
    """One full live measurement (assumes the chip answered the probe)."""
    try:
        params, cfg, desc, model_name, quantize = _build_8b_int8()
    except Exception as e:  # OOM on smaller chips → honest fallback
        print(f"8B int8 build failed ({type(e).__name__}: {e}), "
              f"falling back to 1.1B bf16", file=sys.stderr)
        params, cfg, desc, model_name, quantize = _build_fallback()
    holder = [params]
    del params  # _suite must hold the only reference to free the HBM
    return _suite(holder, cfg, desc, model_name, quantize, BATCH,
                  PROMPT_LEN, GEN_TOKENS, label="")


# -- moe leg: expert-parallel serving at parity (ISSUE 18) ----------------

#: tiny-moe serving geometry (4 experts top-2, GQA GROUP=2) at bench
#: scale — the family the deleted fallback-matrix rows used to demote
_MOE_CFG = mixtral.MixtralConfig(
    vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    ffn_dim=128, n_experts=4, experts_per_token=2, max_seq_len=512,
    rope_theta=10000.0,
)
_MOE_PAGE = 16
#: seeded mixed-length admission burst (byte-tokenizer token counts),
#: fired concurrently so both children coalesce one admission. Five
#: ~97-token prompts share the 128 bucket — the bucketed control pads
#: each to 128 AND pads the 5-row group to 8 rows; the ragged pack
#: pays only the chunk residue of the 685-token total
_MOE_MIX = (97, 97, 97, 97, 97, 200)


async def _drive_moe_burst(s, url: str, model: str, gen_tokens: int,
                           tag: str) -> list[tuple[float, str]]:
    """Fire the MoE mixed-length burst concurrently; returns per-request
    (TTFT ms, generated text) — the text feeds the byte-identity check
    between the ragged+fused child and the bucketed+chained control."""

    async def one(n_tokens: int, i: int) -> tuple[float, str]:
        text = (f"{tag}{i:02d}" + "x" * n_tokens)[: n_tokens - 1]
        payload = {
            "model": model,
            "prompt": text,
            "max_tokens": gen_tokens,
            "temperature": 0.0,
            "stream": True,
            "logit_bias": {"97": 100},
        }
        t0 = time.perf_counter()
        first = -1.0
        out: list[str] = []
        async with s.post(url + "/v1/completions", json=payload) as resp:
            assert resp.status == 200, resp.status
            while True:
                line = await resp.content.readline()
                if not line:
                    break
                line = line.strip()
                if not line.startswith(b"data: "):
                    continue
                data = line[6:]
                if data == b"[DONE]":
                    break
                ev = json.loads(data)
                ch = ev.get("choices") or []
                if ch and ch[0].get("text"):
                    if first < 0:
                        first = (time.perf_counter() - t0) * 1000.0
                    out.append(ch[0]["text"])
        return first, "".join(out)

    return list(await asyncio.gather(
        *(one(n, i) for i, n in enumerate(_MOE_MIX))))


def moe_numbers(reps: int = 3, gen_tokens: int = 8) -> dict:
    """The ``--ab moe`` leg (ISSUE 18): the same seeded mixed-length
    burst against TWO tiny-moe tpuserve children — ragged prefill +
    fused decode (the program families the deleted fallback rows now
    admit MoE to) vs the xla-bucketed + chained control — with reps
    interleaved so host drift cancels. The claims:

    - **byte-identical streams**: expert parity is exactness, not
      closeness — both children serve f32 params/KV and greedy
      sampling, so every generated character must match.
    - **padding tax**: the bucketed child pays bucket + pow2 group-row
      padding; the ragged pack pays only chunk residue (per-child
      padded_frac from the /state token counters).
    - **routing surface**: moe_dropped_frac / moe_expert_imbalance /
      moe_tokens_routed off the child's /state — the gauges the
      gateway picker prices (PR 10 worst-device discipline).
    - zero hot compiles on either child over the timed reps. TTFT
      medians are reference only: the CPU host runs the XLA fallbacks,
      not the DMA-skip kernels."""
    import aiohttp

    model_name = "bench-moe-tiny"
    engine_common = {
        "min_prefill_bucket": 32, "num_pages": 112,
        "max_queued_requests": 64, "kv_cache_dtype": "float32",
        "enable_prefix_cache": False,
        # one coalesced admission is the quantity under test (same
        # rationale as the ragged leg; the wait cancels from the A/B)
        "admission_coalesce_ms": 20.0,
    }
    k = int(os.environ.get("AIGW_BENCH_CPU_K", "4"))
    url_moe, stop_moe = _start_tpuserve_subproc(
        model_name, _MOE_CFG, "", batch=8, k_steps=k,
        engine=dict(engine_common, attention_backend="pallas-ragged",
                    decode_backend="fused"),
        page=_MOE_PAGE, param_dtype="float32", family="mixtral")
    url_ctl, stop_ctl = _start_tpuserve_subproc(
        model_name, _MOE_CFG, "", batch=8, k_steps=k,
        engine=dict(engine_common, attention_backend="xla-bucketed"),
        page=_MOE_PAGE, param_dtype="float32", family="mixtral")

    async def run() -> dict:
        await _wait_health(url_moe, 1200)
        await _wait_health(url_ctl, 1200)
        timeout = aiohttp.ClientTimeout(total=1200)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            # off-the-clock warm pass: compile whatever shapes the warm
            # ladders missed on either leg
            for url in (url_moe, url_ctl):
                await _drive_moe_burst(s, url, model_name, gen_tokens,
                                       "w")
            st_moe0 = await _get_state(s, url_moe)
            st_ctl0 = await _get_state(s, url_ctl)
            moe_runs, ctl_runs = [], []
            for rep in range(reps):
                moe_runs.extend(await _drive_moe_burst(
                    s, url_moe, model_name, gen_tokens, f"r{rep}"))
                ctl_runs.extend(await _drive_moe_burst(
                    s, url_ctl, model_name, gen_tokens, f"r{rep}"))
            st_moe1 = await _get_state(s, url_moe)
            st_ctl1 = await _get_state(s, url_ctl)
        identical = all(a[1] == b[1]
                        for a, b in zip(moe_runs, ctl_runs))
        mt = _median([t for t, _ in moe_runs if t > 0])
        ct = _median([t for t, _ in ctl_runs if t > 0])
        return {
            "moe_ragged_ttft_ms_p50": round(mt, 1),
            "moe_bucketed_ttft_ms_p50": round(ct, 1),
            "moe_identical_streams": identical,
            "moe_backend": st_moe1.get("attention_backend", ""),
            "moe_decode_impl": st_moe1.get("decode_attn_impl", ""),
            "moe_dropped_frac": st_moe1.get("moe_dropped_frac", 0.0),
            "moe_expert_imbalance": st_moe1.get(
                "moe_expert_imbalance", 0.0),
            "moe_tokens_routed": (st_moe1.get("moe_tokens_routed", 0)
                                  - st_moe0.get("moe_tokens_routed", 0)),
            "moe_ab_reps": reps * len(_MOE_MIX),
            **_ragged_ab_fields(st_moe0, st_moe1, "moe_ragged"),
            **_ragged_ab_fields(st_ctl0, st_ctl1, "moe_bucketed"),
        }

    try:
        return asyncio.run(run())
    finally:
        stop_moe()
        stop_ctl()


def _hist_q_bound(h0: dict, h1: dict, q: float) -> float:
    """Quantile BUCKET BOUND from cumulative-histogram deltas over one
    capture window: the smallest finite bucket upper bound whose
    cumulative delta covers ``q`` of the window's observations. Coarse
    by construction (bucket resolution), but server-side — and for the
    batch tier that is the point: the engine's TTFT histogram only ever
    observes interactive streams, so the mixed-phase delta is already
    batch-free with no client filtering."""
    total = h1.get("+Inf", 0) - h0.get("+Inf", 0)
    if total <= 0:
        return 0.0
    finite = sorted(((float(le), le) for le in h1 if le != "+Inf"))
    for bound, le in finite:
        if h1.get(le, 0) - h0.get(le, 0) >= q * total:
            return bound
    return 2.0 * finite[-1][0] if finite else 0.0


# the identity probe's decodable-alphabet bias: +100 on bytes a–z pins
# greedy INSIDE the byte-decodable range (the tiny model's natural
# argmax lands on ids ≥ 256, which the ByteTokenizer drops — the text
# channel would compare empty strings) while WHICH letter wins each
# step still depends on the full KV content — a real byte-identity
# signal that survives tokenizer decode
_IDENT_BIAS = {str(t): 100 for t in range(97, 123)}


async def _batch_submit(s, url: str, model: str, n_lines: int,
                        max_tokens: int, tag: str,
                        logit_bias: bool = True,
                        bias: dict | None = None) -> str:
    """Upload a JSONL input and create a /v1/completions batch; returns
    the batch id. Asserts the submit path never sheds (the never-429
    claim rides every submission the leg makes)."""
    lines = []
    for i in range(n_lines):
        body = {"model": model,
                "prompt": (f"{tag}{i:03d}" + "b" * 64)[:63],
                "max_tokens": max_tokens, "temperature": 0.0}
        if bias is not None:
            body["logit_bias"] = bias
        elif logit_bias:
            body["logit_bias"] = {"97": 100}
        lines.append(json.dumps({
            "custom_id": f"{tag}-{i:03d}", "method": "POST",
            "url": "/v1/completions", "body": body}))
    raw = ("\n".join(lines) + "\n").encode()
    async with s.post(url + "/v1/files", data=raw) as resp:
        assert resp.status == 200, f"file upload {resp.status}"
        fid = (await resp.json())["id"]
    async with s.post(url + "/v1/batches", json={
            "input_file_id": fid,
            "endpoint": "/v1/completions"}) as resp:
        assert resp.status == 200, f"batch create {resp.status}"
        return (await resp.json())["id"]


async def _batch_poll(s, url: str, bid: str,
                      timeout_s: float = 900.0) -> dict:
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        async with s.get(url + f"/v1/batches/{bid}") as resp:
            b = await resp.json()
        if b["status"] in ("completed", "cancelled"):
            return b
        await asyncio.sleep(0.25)
    raise TimeoutError(f"batch {bid} never finalized")


async def _batch_cancel_drain(s, url: str, bid: str) -> dict:
    """Cancel + wait until the batch finalizes AND its engine-side
    footprint (active slots, queued, parked) is gone — the next phase
    must start from a quiet batch tier."""
    async with s.post(url + f"/v1/batches/{bid}/cancel") as resp:
        await resp.read()
    b = await _batch_poll(s, url, bid)
    while True:
        st = await _get_state(s, url)
        if (not st.get("batch_active", 0)
                and not st.get("batch_queued", 0)):
            return b
        await asyncio.sleep(0.1)


async def _batch_texts(s, url: str, b: dict) -> dict[str, str]:
    """custom_id → generated text from a finalized batch's output
    JSONL file."""
    async with s.get(url + f"/v1/files/{b['output_file_id']}/content") \
            as resp:
        assert resp.status == 200, f"output fetch {resp.status}"
        raw = await resp.read()
    out: dict[str, str] = {}
    for ln in raw.decode().splitlines():
        rec = json.loads(ln)
        body = (rec.get("response") or {}).get("body") or {}
        ch = (body.get("choices") or [{}])[0]
        out[rec["custom_id"]] = ch.get("text", "")
    return out


async def _batch_wait_active(s, url: str, min_tokens: int = 0,
                             timeout_s: float = 120.0) -> dict:
    """Wait until the batch tier holds at least one slot (and has
    generated ``min_tokens`` — a parked slot must have generated ≥ 1,
    so the preemption probe waits for real decode progress)."""
    st0 = await _get_state(s, url)
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        st = await _get_state(s, url)
        if (st.get("batch_active", 0) >= 1
                and (st.get("batch_tokens", 0)
                     - st0.get("batch_tokens", 0)) >= min_tokens):
            return st
        await asyncio.sleep(0.1)
    raise TimeoutError("batch tier never went active")


def batch_tier_numbers(reps: int = 3, arrivals: int = 18) -> dict:
    """The ``--ab batch_tier`` leg (ISSUE 19): ONE f32 tpuserve child,
    three phases per rep over the SAME seeded open-loop interactive
    trace — (a) interactive solo, (b) batch solo (the measured
    idle-slot capacity: the tier's ``batch_slot_frac`` ceiling running
    on an otherwise idle engine), (c) interactive + saturating
    /v1/batches backlog. The portable claims:

    - **interactive TTFT unmoved**: server-side TTFT p95 bucket-bound
      ratio solo/mixed ≥ 0.9. The engine's TTFT histogram never
      observes batch streams, so the mixed-phase delta is already the
      interactive class with no client-side filtering.
    - **idle slots soaked**: mixed-phase batch tokens/s ≥ 0.5× the
      batch-solo capacity — the offline tier keeps earning while the
      interactive trace runs over it.
    - **preempt/resume is exact**: off the clock, a batch stream
      parked mid-decode by an interactive burst (the migration-export
      rung of the preemption ladder) finishes with text identical to
      an uninterrupted run of the same line, with state_rebuilds == 0.
    - zero hot XLA compiles across the timed phases; batch submits
      never see a 429 (asserted on every submission)."""
    import aiohttp

    model_name = "bench-batch-tiny"
    url, stop = _start_tpuserve_subproc(
        model_name, CPU_CFG, "", batch=8,
        k_steps=int(os.environ.get("AIGW_BENCH_CPU_K", "4")),
        engine={"kv_cache_dtype": "float32", "num_pages": 96,
                "max_queued_requests": 64, "batch_slot_frac": 0.5},
        param_dtype="float32")

    def mk_trace(seed: int) -> list[dict]:
        return _poisson_trace(seed, arrivals, rate_hz=4.0,
                              prompt_lens=(48, 96), gen_lens=(8, 16),
                              burst_frac=0.3)

    async def pressured_identity(s) -> dict:
        """The off-clock preempt/resume probe: one alphabet-biased
        greedy batch line (see _IDENT_BIAS) run uninterrupted, then
        the same line parked mid-decode by a zero-gap interactive
        burst. Also the warm pass for the park/resume program shapes —
        it runs BEFORE the compile baseline on purpose."""
        bid = await _batch_submit(s, url, model_name, 1, 40, "idsolo",
                                  bias=_IDENT_BIAS)
        texts_a = await _batch_texts(
            s, url, await _batch_poll(s, url, bid))
        st0 = await _get_state(s, url)
        bid = await _batch_submit(s, url, model_name, 1, 40, "idsolo",
                                  bias=_IDENT_BIAS)
        await _batch_wait_active(s, url, min_tokens=2)
        burst = [{"at": 0.0, "prompt_len": 48, "gen": 8,
                  "tenant": "", "i": i} for i in range(12)]
        await _drive_openloop(s, url, model_name, burst, tag="idp")
        texts_b = await _batch_texts(
            s, url, await _batch_poll(s, url, bid))
        st1 = await _get_state(s, url)
        # custom_ids match across runs (same tag), so compare values
        return {
            "batch_tier_identical_streams": (
                list(texts_a.values()) == list(texts_b.values())
                # the bias alphabet decodes 1 char/token: a full-length
                # text proves the comparison never collapsed to ""
                and all(len(t) >= 40 for t in texts_a.values())),
            "batch_tier_preemptions": (st1.get("batch_preemptions", 0)
                                       - st0.get("batch_preemptions",
                                                 0)),
            "batch_tier_resumed": (st1.get("batch_resumed", 0)
                                   - st0.get("batch_resumed", 0)),
            "batch_tier_state_rebuilds": (st1.get("state_rebuilds", 0)
                                          - st0.get("state_rebuilds",
                                                    0)),
        }

    async def run() -> dict:
        await _wait_health(url, 1200)
        timeout = aiohttp.ClientTimeout(total=1200)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            # off-the-clock warm pass: the interactive buckets, the
            # batch prompt bucket, and (via the identity probe) the
            # park/export + resume/import program shapes
            await _drive_openloop(s, url, model_name, mk_trace(1)[:4],
                                  tag="w")
            bid = await _batch_submit(s, url, model_name, 4, 8, "warm")
            await _batch_poll(s, url, bid)
            ident = await pressured_identity(s)

            st_c0 = await _get_state(s, url)
            ttft_ratios, soak_ratios = [], []
            solo_tps_all, mixed_tps_all = [], []
            cl_solo, cl_mixed = [], []
            shed_solo = shed_mixed = 0
            for rep in range(reps):
                trace = mk_trace(7000 + rep)
                # (a) interactive solo
                h0 = await _ttft_hists(s, [url])
                r_solo = await _drive_openloop(s, url, model_name,
                                               trace, tag=f"s{rep}")
                h1 = await _ttft_hists(s, [url])
                # (b) batch-solo capacity window (idle-slot capacity:
                # the ceiling's slots on an otherwise idle engine)
                bid = await _batch_submit(s, url, model_name, 48, 24,
                                          f"bs{rep}")
                stb0 = await _batch_wait_active(s, url)
                tb0 = time.perf_counter()
                await asyncio.sleep(4.0)
                stb1 = await _get_state(s, url)
                tb1 = time.perf_counter()
                await _batch_cancel_drain(s, url, bid)
                solo_tps = ((stb1.get("batch_tokens", 0)
                             - stb0.get("batch_tokens", 0))
                            / (tb1 - tb0))
                # (c) interactive + saturating batch backlog
                bid = await _batch_submit(s, url, model_name, 48, 24,
                                          f"bm{rep}")
                stm0 = await _batch_wait_active(s, url)
                h2 = await _ttft_hists(s, [url])
                tm0 = time.perf_counter()
                r_mixed = await _drive_openloop(s, url, model_name,
                                                trace, tag=f"m{rep}")
                tm1 = time.perf_counter()
                h3 = await _ttft_hists(s, [url])
                stm1 = await _get_state(s, url)
                await _batch_cancel_drain(s, url, bid)
                mixed_tps = ((stm1.get("batch_tokens", 0)
                              - stm0.get("batch_tokens", 0))
                             / (tm1 - tm0))
                p_solo = _hist_q_bound(h0, h1, 0.95)
                p_mixed = _hist_q_bound(h2, h3, 0.95)
                if p_solo > 0 and p_mixed > 0:
                    ttft_ratios.append(p_solo / p_mixed)
                if solo_tps > 0:
                    soak_ratios.append(mixed_tps / solo_tps)
                solo_tps_all.append(solo_tps)
                mixed_tps_all.append(mixed_tps)
                cl_solo.extend(r_solo["client_ttft_ms"])
                cl_mixed.extend(r_mixed["client_ttft_ms"])
                shed_solo += r_solo["shed"]
                shed_mixed += r_mixed["shed"]
            st_c1 = await _get_state(s, url)
        return {
            "batch_tier_interactive_ttft_p95_ratio": round(
                _median(ttft_ratios), 4),
            "batch_tier_ttft_ratio_spread": round(
                _spread(ttft_ratios), 3),
            "batch_tier_client_ttft_p95_solo_ms": round(
                _p95(cl_solo), 1),
            "batch_tier_client_ttft_p95_mixed_ms": round(
                _p95(cl_mixed), 1),
            "batch_tier_soak_ratio": round(_median(soak_ratios), 4),
            "batch_tier_soak_spread": round(_spread(soak_ratios), 3),
            "batch_tier_batch_solo_tps": round(
                _median(solo_tps_all), 1),
            "batch_tier_batch_mixed_tps": round(
                _median(mixed_tps_all), 1),
            "batch_tier_interactive_shed_solo": shed_solo,
            "batch_tier_interactive_shed_mixed": shed_mixed,
            "batch_tier_slot_frac": st_c1.get("batch_slot_frac", 0.0),
            "batch_tier_hot_compiles": (st_c1.get("xla_compiles", 0)
                                        - st_c0.get("xla_compiles", 0)),
            "batch_tier_ab_reps": reps,
            **ident,
        }

    try:
        return asyncio.run(run())
    finally:
        stop()


def run_cpu_ratio() -> dict:
    """Chip-independent north-star *ratio* on the CPU backend (honest
    fallback when the tunnel is down all round): same harness, small
    model, absolute tok/s NOT comparable to TPU numbers. K=4 instead of
    the tunnel-tuned 16: on a 1-core host a 16-step window is >1s, and
    TTFT becomes a lottery over which requests wait out an in-flight
    window — the quantity measured stops being the gateway."""
    params = llama.init_params(jax.random.PRNGKey(0), CPU_CFG)
    jax.block_until_ready(params)
    holder = [params]
    del params
    res = _suite(
        holder, CPU_CFG, "0.02B llama-arch bf16", "bench-cpu-tiny", "",
        batch=BATCH, prompt_len=64, gen_tokens=64,
        label="CPU BACKEND (TPU tunnel down; ratio is the signal, "
              "absolute tok/s is not): ",
        k_steps=int(os.environ.get("AIGW_BENCH_CPU_K", "4")),
        subproc=True, reps=5,
    )
    res["backend"] = jax.default_backend()
    # gateway_prefix + spec_decode legs ride the same JSON line (a leg
    # failure must not zero the headline capture)
    try:
        res.update(prefix_cache_numbers())
    except Exception as e:
        print(f"gateway_prefix leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        res.update(spec_decode_numbers())
    except Exception as e:
        print(f"spec_decode leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        res.update(ragged_prefill_numbers())
    except Exception as e:
        print(f"ragged_prefill leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        res.update(lora_numbers())
    except Exception as e:
        print(f"lora leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        res.update(disagg_numbers())
    except Exception as e:
        print(f"disagg leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        res.update(slo_routing_numbers())
    except Exception as e:
        print(f"slo_routing leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        res.update(structured_numbers())
    except Exception as e:
        print(f"structured leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        res.update(mesh_numbers())
    except Exception as e:
        print(f"mesh leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        res.update(kv_tier_numbers())
    except Exception as e:
        print(f"kv_tier leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        res.update(fleet_obs_numbers())
    except Exception as e:
        print(f"fleet_obs leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        res.update(decode_fused_numbers())
    except Exception as e:
        print(f"decode_fused leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        res.update(fleet_ctl_numbers())
    except Exception as e:
        print(f"fleet_ctl leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        res.update(longctx_numbers())
    except Exception as e:
        print(f"longctx leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        res.update(moe_numbers())
    except Exception as e:
        print(f"moe leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        res.update(batch_tier_numbers())
    except Exception as e:
        print(f"batch_tier leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        res.update(metering_numbers())
    except Exception as e:
        print(f"metering leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    return res


def _cpu_ratio_via_subprocess() -> dict | None:
    """Run --cpu-gateway-ratio in a JAX_PLATFORMS=cpu subprocess (this
    process's jax may be wedged on the dead TPU tunnel)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--cpu-gateway-ratio"],
            env=env, capture_output=True, text=True, timeout=1800,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    print(out.stderr[-2000:], file=sys.stderr)
    return None


def _bench_lock():
    """One bench at a time: the oppo.sh capture loop and the driver's
    end-of-round run must not overlap on a 1-core host (two concurrent
    suites measure each other). Tries for 15 min, then proceeds with a
    warning rather than deadlocking the driver."""
    import fcntl

    here = os.path.dirname(os.path.abspath(__file__))
    f = open(os.path.join(here, "benchmarks", ".bench.lock"), "w")
    deadline = time.time() + 900
    while True:
        try:
            fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return f
        except OSError:
            if time.time() > deadline:
                print("bench lock busy for 15min — proceeding anyway",
                      file=sys.stderr)
                return f
            time.sleep(5)


def main() -> None:
    from benchmarks import persist

    # the --cpu-gateway-ratio leg runs as a child of a lock-holding
    # bench.py (or directly in a dev loop) — locking there would deadlock
    lock = None  # held for process lifetime  # noqa: F841
    if "--cpu-gateway-ratio" not in sys.argv:
        lock = _bench_lock()

    if "--ab" in sys.argv:
        idx = sys.argv.index("--ab")
        target = sys.argv[idx + 1] if idx + 1 < len(sys.argv) else ""
        if target == "prefix_cache":
            result = prefix_cache_numbers()
            result["metric"] = (
                "gateway_prefix interleaved A/B — prefix_cache on vs "
                "off, shared 64-token system-prompt head, ~96-token "
                "prompts, sequential streaming chats on the CPU "
                "backend; the warm/cold ratio is the signal, absolute "
                "ms is not")
        elif target == "spec_decode":
            result = spec_decode_numbers()
            result["metric"] = (
                "spec_decode interleaved A/B — speculative decoding on "
                "vs off, decode-heavy sequential streaming chats on "
                "the CPU backend: repetitive leg (n-gram drafts "
                "accept) and forced low-acceptance leg (adaptive "
                "ladder collapses to plain decode); the tok/s ratios "
                "are the signal, absolute tok/s is not")
        elif target == "ragged_prefill":
            result = ragged_prefill_numbers()
            result["metric"] = (
                "ragged_prefill interleaved A/B — attention backend "
                "pallas-ragged vs xla-bucketed on the same "
                "mixed-length admission burst (5×~97 + 1×1024 tokens) "
                "on the CPU backend: padded_frac (padding tax) and the "
                "warm compile surface are the signal; absolute TTFT "
                "is not (the CPU child runs the XLA windowed fallback, "
                "not the DMA-skip kernel)")
        elif target == "lora":
            result = lora_numbers()
            result["metric"] = (
                "lora interleaved A/B — adapter-mix traffic (rotating "
                "LoRA adapters, model '<base>:t{i}') vs base-only "
                "traffic (the zero-row control) on ONE 5-adapter/"
                "4-row tpuserve child, decode-heavy sequential "
                "streaming chats on the CPU backend; the tok/s ratio "
                "(parity), zero hot compiles across mix changes and "
                "the evict/reload churn phase, and the load/eviction "
                "counters are the signal — absolute tok/s is not")
        elif target == "disagg":
            result = disagg_numbers()
            result["metric"] = (
                "disagg interleaved A/B — prefill/decode disaggregation "
                "over two tpuserve replicas: a session streamed on A is "
                "exported after its first tokens and resumed on B via "
                "KV page migration; resume TTFT vs a cold prefill of "
                "the same total length (target ≤ 0.6), plus a gateway "
                "migration-on/off open-loop pass; ratios are the "
                "signal, absolute ms is not (CPU backend)")
        elif target == "slo_routing":
            result = slo_routing_numbers()
            result["metric"] = (
                "slo_routing A/B — the same seeded open-loop Poisson "
                "trace through a picker_mode=slo gateway (predicted-"
                "TTFT routing + 429 shed) vs a static-score gateway "
                "over the same heterogeneous 2-replica pool; goodput-"
                "under-SLO from server-side TTFT histograms is the "
                "signal (CPU backend)")
        elif target == "structured":
            result = structured_numbers()
            result["metric"] = (
                "structured A/B — grammar-constrained decoding (ISSUE "
                "9): the same seeded open-loop arrival schedule against "
                "one speculation-on tpuserve child, 25% of arrivals "
                "asking for json_schema output vs an all-plain control "
                "at matched token volume; 100% schema-valid constrained "
                "responses, zero hot XLA compiles, and the mixed/plain "
                "throughput ratio (constraint bookkeeping price) are "
                "the signal (CPU backend)")
        elif target == "mesh":
            result = mesh_numbers()
            result["metric"] = (
                "mesh A/B — tensor-parallel serving at parity (ISSUE "
                "10): the same seeded mixed-feature streaming traffic "
                "against a tp=8 child (8 virtual CPU devices, params + "
                "paged KV sharded per the TP layout) vs a single-"
                "device child; byte-identical streams, per-device "
                "parameter bytes ≈ total/tp, and zero hot compiles on "
                "the warmed mesh path are the signal — the throughput "
                "ratio is informational on CPU (virtual devices time-"
                "slice one core)")
        elif target == "kv_tier":
            result = kv_tier_numbers()
            result["metric"] = (
                "kv_tier A/B — fleet KV memory hierarchy (ISSUE 11): "
                "shared-prefix open-loop bursts against replica B with "
                "sibling A warm — warm fleet (A named in x-aigw-kv-"
                "peers: arrival 0 fetches A's pages over /kv/pages and "
                "resumes) vs cold fleet (unprimed head, full prefill); "
                "first-arrival TTFT ratio ≤ 0.6 is the claim, plus "
                "spill→revive churn counters on A's host tier and a "
                "zero-hot-compile delta across the churn (CPU backend; "
                "ratios are the signal)")
        elif target == "fleet_obs":
            result = fleet_obs_numbers()
            result["metric"] = (
                "fleet_obs A/B — the fleet observability plane (ISSUE "
                "12) must be ~free: the same seeded open-loop trace "
                "through a gateway with the decision ring + burn-rate "
                "monitor on and a 4Hz /fleet/metrics federation "
                "scraper running, vs everything off; throughput ratio "
                "≥ 0.95 and zero hot XLA compiles are the claim (CPU "
                "backend)")
        elif target == "decode_fused":
            result = decode_fused_numbers()
            result["metric"] = (
                "decode_fused interleaved A/B — fused decode step + "
                "quantized KV pages (ISSUE 13): the same greedy "
                "decode-heavy chats against fused-vs-chained f32 "
                "children (streams must be identical; tok/s ratio is "
                "bookkeeping parity on the CPU backend — the kernel's "
                "HBM win needs the on-chip capture) and an int8-KV "
                "fused child (bytes/token ≤ 0.55x bf16 and greedy "
                "agreement vs the native child are the capacity/"
                "quality signals)")
        elif target == "fleet_ctl":
            result = fleet_ctl_numbers()
            result["metric"] = (
                "fleet_ctl chaos A/B — the fleet control plane (ISSUE "
                "14) under injected churn: the seeded open-loop trace "
                "over a controller-enabled 2-replica pool with one "
                "kill -9 mid-decode (failover: re-route + replacement "
                "launch through the local launcher) and one flood-"
                "triggered scale-out (the SLO monitor's sustained-"
                "overshoot predicate); dropped (torn) streams == 0, "
                "goodput recovery ≥0.9× the pre-event window in a "
                "bounded reported time, and zero hot XLA compiles on "
                "the surviving replica are the claims (CPU backend)")
        elif target == "longctx":
            result = longctx_numbers()
            result["metric"] = (
                "longctx A/B — sequence-sharded chunked prefill "
                "(ISSUE 17): the same long-context traffic against "
                "two sp=8 children (8 virtual CPU devices) — chunked "
                "vs monolithic sp prefill; short interactive streams "
                "fired mid-long-prefill admit at chunk boundaries "
                "(interactive TTFT p95 ≥ 2× better chunked) and a "
                "primed head + continuation resumes the chunk loop "
                "at the adopted page offset (warm/cold TTFT ≤ 0.6); "
                "padded_frac < 0.05 on the chunk rung ladder and "
                "zero hot XLA compiles at long-context geometry are "
                "the guardrails (CPU backend; ratios are the signal)")
        elif target == "moe":
            result = moe_numbers()
            result["metric"] = (
                "moe interleaved A/B — expert-parallel serving at "
                "parity (ISSUE 18): the same seeded mixed-length "
                "burst against a tiny-moe ragged+fused child vs the "
                "xla-bucketed+chained control (the two deleted "
                "fallback-matrix rows); byte-identical streams, the "
                "padded_frac gap, zero hot compiles, and the "
                "moe_dropped_frac / expert-imbalance routing gauges "
                "are the signal — absolute TTFT is not (CPU backend "
                "runs the XLA fallbacks, not the DMA-skip kernels)")
        elif target == "batch_tier":
            result = batch_tier_numbers()
            result["metric"] = (
                "batch_tier A/B — priority-tiered serving (ISSUE 19): "
                "the same seeded open-loop interactive trace against "
                "one f32 child, solo vs over a saturating /v1/batches "
                "backlog; interactive TTFT p95 ratio ≥ 0.9 from the "
                "server-side histogram (which never observes batch "
                "streams), mixed batch tokens ≥ 0.5× the measured "
                "batch-solo idle-slot capacity, zero hot XLA "
                "compiles, never a 429 on batch submits, and an "
                "off-clock preempt-mid-decode/resume run whose text "
                "is identical to the uninterrupted run with "
                "state_rebuilds == 0 (CPU backend; ratios are the "
                "signal)")
        elif target == "metering":
            result = metering_numbers()
            result["metric"] = (
                "metering A/B — engine-truth usage metering (ISSUE "
                "20) must be ~free: the same seeded open-loop trace "
                "through a gateway journaling every MeterRecord into "
                "the windowed per-tenant ledger with a meter-variable "
                "CostProgram pricing each request and a 4Hz /usage + "
                "/metrics poller running, vs usage disabled; "
                "throughput ratio ≥ 0.95, zero hot XLA compiles, and "
                "ledger record count == completed trace requests are "
                "the claims (CPU backend)")
        else:
            print(json.dumps({"error": f"unknown --ab target {target!r}; "
                              "supported: prefix_cache, spec_decode, "
                              "ragged_prefill, lora, disagg, "
                              "slo_routing, structured, mesh, "
                              "kv_tier, fleet_obs, decode_fused, "
                              "fleet_ctl, longctx, moe, batch_tier, "
                              "metering"}))
            return
        print(json.dumps(result))
        return

    if "--cpu-gateway-ratio" in sys.argv:
        result = run_cpu_ratio()
        if not result.get("harness_stable", True):
            # one retry: a transient load spike (test suite, compile)
            # shouldn't burn the round's persisted capture
            result = run_cpu_ratio()
        if result.get("harness_stable", True):
            persist.save("gateway_cpu", result)
        print(json.dumps(result))
        return

    if _chip_responsive():
        result = run_live()
        # persist only real-chip runs: a CPU run (JAX_PLATFORMS=cpu dev
        # loop) passing the probe must not overwrite on-chip history
        if jax.default_backend() == "tpu":
            persist.save("headline", result)
        print(json.dumps(result))
        return
    # Tunnel down at bench time (it comes and goes): report the latest
    # measurement persisted by an earlier run this round rather than a
    # zero — with its age, so the number's provenance is explicit.
    prior = persist.latest("headline")
    if prior is not None:
        age = persist.age_hours(prior)
        result = dict(prior)
        result["metric"] = (
            f"{prior['metric']} — persisted measurement from "
            f"{prior.get('captured_at', '?')} "
            f"({age:.1f}h old; tunnel down at bench time)"
            if age is not None else prior["metric"]
        )
        print(json.dumps(result))
        return
    # No on-chip run exists at all: fall back to the chip-independent
    # CPU-backend ratio (persisted this round, else measured now).
    prior = persist.latest("gateway_cpu")
    if prior is None:
        prior = _cpu_ratio_via_subprocess()
    if prior is not None:
        result = dict(prior)
        age = persist.age_hours(prior)
        if age is not None:
            result["metric"] = (
                f"{prior['metric']} — persisted {age:.1f}h before bench "
                f"time; TPU tunnel down all round"
            )
        print(json.dumps(result))
        return
    print(
        json.dumps(
            {
                "metric": (
                    "gateway tokens/sec — TPU tunnel unresponsive at "
                    "bench time, no persisted run exists, and the CPU "
                    "ratio harness failed"
                ),
                "value": 0,
                "unit": "tokens/s",
                "vs_baseline": 0,
            }
        )
    )


if __name__ == "__main__":
    main()
